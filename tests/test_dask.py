"""Dask multi-worker training tests (reference
python-package/lightgbm/dask.py + tests/python_package_test/test_dask.py).

These run the REAL per-worker flow: LocalCluster with separate worker
processes, per-partition data placement, machines-list injection, and the
jax.distributed rendezvous inside each worker. They are skipped when
dask/distributed are not installed (this image ships without them — see
README "Environment status"); run `pip install dask distributed` in a dev
environment to exercise them.
"""

import numpy as np
import pytest

dask = pytest.importorskip("dask")
distributed = pytest.importorskip("distributed")

import dask.array as da                              # noqa: E402
from distributed import Client, LocalCluster         # noqa: E402

import lightgbm_tpu as lgb                           # noqa: E402
from lightgbm_tpu.dask import DaskLGBMClassifier, DaskLGBMRegressor  # noqa: E402

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def client():
    cluster = LocalCluster(n_workers=2, threads_per_worker=1,
                           processes=True, dashboard_address=None)
    c = Client(cluster)
    yield c
    c.close()
    cluster.close()


def _data(n=4000, f=8, seed=0, chunks=4):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    dX = da.from_array(X, chunks=(n // chunks, f))
    dy = da.from_array(y, chunks=(n // chunks,))
    return X, y, dX, dy


class TestDaskTraining:
    def test_classifier_multi_worker_fit_predict(self, client):
        X, y, dX, dy = _data()
        clf = DaskLGBMClassifier(n_estimators=10, num_leaves=15,
                                 verbosity=-1)
        clf.fit(dX, dy)
        pred = clf.predict(dX).compute()
        assert ((pred == y).mean()) > 0.9

    def test_parity_vs_local_fit(self, client):
        X, y, dX, dy = _data()
        clf = DaskLGBMClassifier(n_estimators=10, num_leaves=15,
                                 verbosity=-1)
        clf.fit(dX, dy)
        local = lgb.LGBMClassifier(n_estimators=10, num_leaves=15,
                                   verbosity=-1, tree_learner="data")
        local.fit(X, y)
        p_d = clf.predict_proba(dX).compute()[:, 1]
        p_l = local.predict_proba(X)[:, 1]
        # distributed bin mappers come from a two-rank sample union;
        # the fitted function must agree closely, not bit-exactly
        assert np.mean(np.abs(p_d - p_l)) < 0.02

    def test_regressor_multi_worker(self, client):
        r = np.random.RandomState(1)
        X = r.randn(4000, 6)
        y = (X[:, 0] * 2 + X[:, 1] ** 2).astype(np.float32)
        dX = da.from_array(X, chunks=(1000, 6))
        dy = da.from_array(y, chunks=(1000,))
        reg = DaskLGBMRegressor(n_estimators=10, num_leaves=15,
                                verbosity=-1)
        reg.fit(dX, dy)
        pred = reg.predict(dX).compute()
        ss_res = np.sum((pred - y) ** 2)
        ss_tot = np.sum((y - y.mean()) ** 2)
        assert 1 - ss_res / ss_tot > 0.7

    def test_classifier_global_class_set(self, client):
        # rank-local partitions may miss classes; the global label
        # encoding must still cover all of them (reference dask.py
        # _train: client-side unique over the collection)
        r = np.random.RandomState(2)
        X = r.randn(4000, 5)
        y = np.zeros(4000, np.int32)
        y[:1000] = 2          # class 2 only in the first partition
        y[1000:] = (X[1000:, 0] > 0).astype(np.int32)
        dX = da.from_array(X, chunks=(1000, 5))
        dy = da.from_array(y, chunks=(1000,))
        clf = DaskLGBMClassifier(n_estimators=5, num_leaves=7,
                                 verbosity=-1)
        clf.fit(dX, dy)
        assert set(np.unique(clf.classes_)) == {0, 1, 2}
        assert clf.predict_proba(dX).compute().shape[1] == 3
