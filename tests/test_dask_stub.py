"""The Dask orchestration EXECUTED via the in-repo stub (VERDICT r3
item 4): lightgbm_tpu/dask.py's partition grouping, who_has worker
assignment, machines injection, per-worker jax.distributed rendezvous,
and rank-0 model return all actually run — in two spawned worker
processes — without dask installed.

Reference analog: python-package/lightgbm/dask.py backed by the
executed test_dask.py suite on distributed.LocalCluster workers. The
real-dask version of these tests lives in tests/test_dask.py and runs
wherever dask exists.
"""

import numpy as np
import pytest

from lightgbm_tpu.testing import dask_stub

from conftest import make_binary


@pytest.fixture(scope="module")
def lgb_dask():
    mod = dask_stub.install()
    yield mod
    dask_stub.uninstall()


class TestStubMechanics:
    """Default-tier: the client machinery itself (no training)."""

    def test_submit_compute_who_has_run(self, lgb_dask):
        client = dask_stub.StubClient(n_workers=2)
        try:
            info = client.scheduler_info()["workers"]
            assert len(info) == 2
            # submit with a future argument dereferenced worker-side
            w = sorted(info)[0]
            a = client.submit(lambda: np.arange(4), workers=[w],
                              pure=False)
            b = client.submit(lambda x: x * 2, a, workers=[w], pure=False)
            np.testing.assert_array_equal(b.result(), np.arange(4) * 2)
            # delayed partition tuples: compute + who_has grouping
            arr = dask_stub.array_from(np.arange(12).reshape(6, 2), 2)
            parts = [dask_stub.delayed(tuple)([d])
                     for d in arr.to_delayed()]
            futs = client.compute(parts)
            who = client.who_has(futs)
            assert set(who) == {f.key for f in futs}
            assert all(len(v) == 1 for v in who.values())
            # run() executes on every listed worker
            ports = client.run(_free_port_count, workers=sorted(info))
            assert set(ports) == set(info)
        finally:
            client.close()

    def test_array_surface(self):
        X = np.random.RandomState(0).randn(10, 3)
        d = dask_stub.array_from(X, 4)
        assert d.shape == (10, 3) and d.ndim == 2
        assert d.chunks[0] == (4, 4, 2)
        np.testing.assert_array_equal(d.compute(), X)
        m = d.map_blocks(lambda b: b[:, 0])
        np.testing.assert_array_equal(m.compute(), X[:, 0])


def _free_port_count():
    return 1


@pytest.mark.slow
class TestDaskTraining:
    """Two spawned workers, real rendezvous, real data-parallel fit."""

    def test_two_worker_classifier(self, lgb_dask):
        X, y = make_binary(n=1200, f=6, seed=5)
        client = dask_stub.StubClient(n_workers=2)
        try:
            dX = dask_stub.array_from(X, 300)
            dy = dask_stub.array_from(y, 300)
            clf = lgb_dask.DaskLGBMClassifier(
                client=client, n_estimators=10, num_leaves=7,
                min_child_samples=5, verbosity=-1)
            clf.fit(dX, dy)
            assert clf._local._Booster.current_iteration() == 10
            # the injected machines params reached the model record
            mstr = clf._local._Booster.model_to_string()
            assert "num_machines: 2" in mstr
            # per-partition predict returns a stub collection
            preds = clf.predict(dX)
            acc = ((preds.compute() > 0.5) == (y > 0.5)).mean() \
                if preds.compute().dtype != np.int64 else \
                (preds.compute() == y).mean()
            assert acc > 0.85
            # distributed training tracks a local single-process fit
            local = lgb_dask.DaskLGBMClassifier(
                n_estimators=10, num_leaves=7, min_child_samples=5,
                verbosity=-1).to_local()
            local.fit(X, y)
            pl = local.predict_proba(X)[:, 1]
            pd_ = clf.predict_proba(dX).compute()[:, 1]
            assert np.corrcoef(pl, pd_)[0, 1] > 0.98
        finally:
            client.close()

    def test_missing_class_on_one_worker(self, lgb_dask):
        # global class set: worker partitions that miss a class must
        # still encode labels identically (dask.py classes override)
        rng = np.random.RandomState(2)
        X = rng.randn(900, 5)
        y = np.zeros(900)
        y[X[:, 0] > 0.3] = 1
        y[X[:, 1] > 0.9] = 2
        # order rows so the last partitions hold every class-2 row
        order = np.argsort(y == 2, kind="stable")
        X, y = X[order], y[order]
        client = dask_stub.StubClient(n_workers=2)
        try:
            clf = lgb_dask.DaskLGBMClassifier(
                client=client, n_estimators=5, num_leaves=7,
                min_child_samples=5, verbosity=-1)
            clf.fit(dask_stub.array_from(X, 225),
                    dask_stub.array_from(y, 225))
            assert list(clf._local._classes) == [0.0, 1.0, 2.0]
            proba = clf.predict_proba(dask_stub.array_from(X, 225))
            assert proba.compute().shape == (900, 3)
        finally:
            client.close()
