"""Distributed learner tests on the 8-virtual-device CPU mesh.

Reference analog: tests/distributed/_test_distributed.py trains the CLI
binary over localhost sockets and checks accuracy; here the same
data/feature/voting-parallel semantics run as shard_map programs, asserting
(a) they produce trees equivalent to the serial learner and (b) accuracy.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.learner.grower import grow_tree
from lightgbm_tpu.learner.split import SplitHyperParams
from lightgbm_tpu.parallel import CommSpec, make_mesh
from lightgbm_tpu.parallel.learner import make_sharded_grower

from conftest import make_binary


def _setup(n=4096, f=12, max_bin=63):
    X, y = make_binary(n=n, f=f)
    ds = lgb.Dataset(X, label=y)
    ds.params["max_bin"] = max_bin
    b = ds.binned
    grad = jnp.asarray(-(y - y.mean()), jnp.float32)
    hess = jnp.ones(n, jnp.float32)
    cnt = jnp.ones(n, jnp.float32)
    args = (jnp.asarray(b.bins), grad, hess, cnt,
            jnp.ones(b.num_features, jnp.float32),
            jnp.asarray(b.num_bins), jnp.asarray(b.missing_types == 2),
            jnp.asarray(b.is_categorical))
    return args, int(b.num_bins.max())


NUM_DEV = len(jax.devices())


@pytest.mark.skipif(NUM_DEV < 2, reason="needs multi-device")
class TestShardedGrower:
    def _grow_serial(self, args, bmax, **kw):
        return grow_tree(*args, num_leaves=15, max_depth=-1,
                         hp=SplitHyperParams(), bmax=bmax, **kw)

    def _grow_parallel(self, args, bmax, mode, ndev=4):
        mesh = make_mesh(ndev)
        comm = CommSpec(axis="data", mode=mode, num_devices=ndev)
        grower = make_sharded_grower(mesh, comm, num_leaves=15, max_depth=-1,
                                     hp=SplitHyperParams(), leafwise=False,
                                     bmax=bmax)
        with mesh:
            return grower(*args)

    def test_data_parallel_matches_serial(self):
        args, bmax = _setup()
        tree_s, rn_s = self._grow_serial(args, bmax)
        tree_p, rn_p = self._grow_parallel(args, bmax, "data")
        # identical structure: same split features/thresholds/gains
        nn = int(tree_s.num_nodes)
        assert int(tree_p.num_nodes) == nn
        np.testing.assert_array_equal(
            np.asarray(tree_s.split_feature[:nn]),
            np.asarray(tree_p.split_feature[:nn]))
        np.testing.assert_array_equal(
            np.asarray(tree_s.threshold_bin[:nn]),
            np.asarray(tree_p.threshold_bin[:nn]))
        np.testing.assert_allclose(np.asarray(tree_s.leaf_value[:nn]),
                                   np.asarray(tree_p.leaf_value[:nn]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(rn_s), np.asarray(rn_p))

    def test_feature_parallel_matches_serial(self):
        args, bmax = _setup()
        tree_s, _ = self._grow_serial(args, bmax)
        tree_p, _ = self._grow_parallel(args, bmax, "feature")
        nn = int(tree_s.num_nodes)
        assert int(tree_p.num_nodes) == nn
        np.testing.assert_array_equal(
            np.asarray(tree_s.split_feature[:nn]),
            np.asarray(tree_p.split_feature[:nn]))
        np.testing.assert_allclose(np.asarray(tree_s.gain[:nn]),
                                   np.asarray(tree_p.gain[:nn]),
                                   rtol=1e-4, atol=1e-5)

    def test_data_parallel_mxu_matches_serial_mxu(self):
        # the MXU grower inside shard_map (per-pass histogram psum, the
        # reference's data-parallel Reduce-Scatter) must grow the same
        # tree as the serial MXU grower on unsharded data
        from lightgbm_tpu.learner.grower_mxu import grow_tree_mxu
        args, bmax = _setup()
        tree_s, rn_s = grow_tree_mxu(
            *args, num_leaves=15, max_depth=-1, hp=SplitHyperParams(),
            bmax=bmax, interpret=True, overshoot=2.0)
        ndev = 4
        mesh = make_mesh(ndev)
        comm = CommSpec(axis="data", mode="data", num_devices=ndev)
        grower = make_sharded_grower(
            mesh, comm, num_leaves=15, max_depth=-1,
            hp=SplitHyperParams(), leafwise=False, bmax=bmax,
            use_mxu=True, interpret=True,
            mxu_kwargs=dict(overshoot=2.0))
        with mesh:
            tree_p, rn_p = grower(*args)
        nn = int(tree_s.num_nodes)
        assert int(tree_p.num_nodes) == nn
        np.testing.assert_array_equal(
            np.asarray(tree_s.split_feature[:nn]),
            np.asarray(tree_p.split_feature[:nn]))
        np.testing.assert_array_equal(
            np.asarray(tree_s.threshold_bin[:nn]),
            np.asarray(tree_p.threshold_bin[:nn]))
        np.testing.assert_allclose(np.asarray(tree_s.leaf_value[:nn]),
                                   np.asarray(tree_p.leaf_value[:nn]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(rn_s), np.asarray(rn_p))

    def test_voting_parallel_grows_good_tree(self):
        # voting is approximate (top-k feature aggregation); check the tree
        # splits on informative features and fits
        args, bmax = _setup()
        tree_p, rn = self._grow_parallel(args, bmax, "voting")
        assert int(tree_p.num_leaves) == 15
        grad = np.asarray(args[1])
        pred = np.asarray(tree_p.leaf_value)[np.asarray(rn)]
        corr = np.corrcoef(pred, -grad)[0, 1]
        assert corr > 0.5

    @pytest.mark.parametrize("ndev", [2, 8])
    def test_device_counts(self, ndev):
        args, bmax = _setup()
        tree_s, _ = self._grow_serial(args, bmax)
        mesh = make_mesh(ndev)
        comm = CommSpec(axis="data", mode="data", num_devices=ndev)
        grower = make_sharded_grower(mesh, comm, num_leaves=15, max_depth=-1,
                                     hp=SplitHyperParams(), leafwise=False,
                                     bmax=bmax)
        with mesh:
            tree_p, _ = grower(*args)
        nn = int(tree_s.num_nodes)
        np.testing.assert_array_equal(
            np.asarray(tree_s.split_feature[:nn]),
            np.asarray(tree_p.split_feature[:nn]))


@pytest.mark.skipif(NUM_DEV < 2, reason="needs multi-device")
class TestDistributedTraining:
    @pytest.mark.parametrize("learner", ["data", "feature", "voting"])
    def test_end_to_end_accuracy(self, learner):
        X, y = make_binary(n=4096)
        bst = lgb.train({"objective": "binary", "tree_learner": learner,
                         "num_devices": 4, "verbosity": -1,
                         "num_leaves": 15}, lgb.Dataset(X, label=y), 20)
        from lightgbm_tpu.metrics import AUCMetric
        auc = AUCMetric._auc_fast(bst.predict(X), y > 0, np.ones(len(y)))
        assert auc > 0.93, (learner, auc)

    def test_data_parallel_equals_serial_model(self):
        X, y = make_binary(n=4096)
        params = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
                  "min_data_in_leaf": 20}
        bst_s = lgb.train(dict(params), lgb.Dataset(X, label=y), 10)
        bst_p = lgb.train(dict(params, tree_learner="data", num_devices=4),
                          lgb.Dataset(X, label=y), 10)
        np.testing.assert_allclose(bst_s.predict(X), bst_p.predict(X),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.skipif(NUM_DEV < 2, reason="needs multi-device")
class TestVotingParity:
    """PV-Tree equivalence (VERDICT r1 weak #3): when the vote's top-2k
    selection covers every feature, voting-parallel must equal full
    data-parallel aggregation — and therefore the serial learner —
    exactly (voting_parallel_tree_learner.cpp:62-78 reduces to the
    data-parallel path when all columns are selected)."""

    def test_voting_matches_serial_when_vote_covers_features(self):
        args, bmax = _setup(f=6)  # f <= 2*top_k (default 20)
        tree_s, rn_s = grow_tree(*args, num_leaves=15, max_depth=-1,
                                 hp=SplitHyperParams(), bmax=bmax)
        ndev = 4
        mesh = make_mesh(ndev)
        comm = CommSpec(axis="data", mode="voting", num_devices=ndev,
                        top_k=20)
        grower = make_sharded_grower(mesh, comm, num_leaves=15,
                                     max_depth=-1, hp=SplitHyperParams(),
                                     leafwise=False, bmax=bmax)
        with mesh:
            tree_p, rn_p = grower(*args)
        nn = int(tree_s.num_nodes)
        assert int(tree_p.num_nodes) == nn
        np.testing.assert_array_equal(
            np.asarray(tree_s.split_feature[:nn]),
            np.asarray(tree_p.split_feature[:nn]))
        np.testing.assert_array_equal(
            np.asarray(tree_s.threshold_bin[:nn]),
            np.asarray(tree_p.threshold_bin[:nn]))
        np.testing.assert_allclose(np.asarray(tree_s.leaf_value[:nn]),
                                   np.asarray(tree_p.leaf_value[:nn]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(rn_s), np.asarray(rn_p))


@pytest.mark.skipif(NUM_DEV < 2, reason="needs multi-device")
class TestDistributedFeatureSampling:
    """feature_fraction_bynode / extra_trees / interaction constraints
    under distributed learners (VERDICT r1 weak #4: previously warned
    and ignored). The replicated rng key makes every shard sample the
    identical masks, so sharded growth equals serial growth with the
    same key."""

    def test_bynode_data_parallel_matches_serial(self):
        args, bmax = _setup()
        key = jax.random.PRNGKey(11)
        tree_s, rn_s = grow_tree(
            *args, num_leaves=15, max_depth=-1, hp=SplitHyperParams(),
            bmax=bmax, feature_fraction_bynode=0.5, rng_key=key)
        ndev = 4
        mesh = make_mesh(ndev)
        comm = CommSpec(axis="data", mode="data", num_devices=ndev)
        grower = make_sharded_grower(
            mesh, comm, num_leaves=15, max_depth=-1, hp=SplitHyperParams(),
            leafwise=False, bmax=bmax, feature_fraction_bynode=0.5,
            with_rng=True)
        with mesh:
            tree_p, rn_p = grower(*args, key)
        nn = int(tree_s.num_nodes)
        assert int(tree_p.num_nodes) == nn
        np.testing.assert_array_equal(
            np.asarray(tree_s.split_feature[:nn]),
            np.asarray(tree_p.split_feature[:nn]))
        np.testing.assert_array_equal(np.asarray(rn_s), np.asarray(rn_p))

    def test_interaction_constraints_distributed(self):
        args, bmax = _setup()
        groups = ((0, 1, 2), (3, 4, 5, 6, 7, 8, 9, 10, 11))
        tree_s, _ = grow_tree(
            *args, num_leaves=15, max_depth=-1, hp=SplitHyperParams(),
            bmax=bmax, interaction_groups=groups)
        ndev = 4
        mesh = make_mesh(ndev)
        comm = CommSpec(axis="data", mode="data", num_devices=ndev)
        grower = make_sharded_grower(
            mesh, comm, num_leaves=15, max_depth=-1, hp=SplitHyperParams(),
            leafwise=False, bmax=bmax, interaction_groups=groups)
        with mesh:
            tree_p, _ = grower(*args)
        nn = int(tree_s.num_nodes)
        assert int(tree_p.num_nodes) == nn
        np.testing.assert_array_equal(
            np.asarray(tree_s.split_feature[:nn]),
            np.asarray(tree_p.split_feature[:nn]))

    def test_engine_level_bynode_distributed(self):
        # end-to-end through lgb.train with tree_learner=data: no more
        # "ignoring them" warning path
        X, y = make_binary(n=4096, f=12)
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "tree_learner": "data", "num_leaves": 15,
                         "feature_fraction_bynode": 0.6,
                         "extra_trees": True}, lgb.Dataset(X, label=y), 8)
        pred = bst.predict(X)
        assert ((pred > 0.5) == y).mean() > 0.7


@pytest.mark.skipif(NUM_DEV < 2, reason="needs multi-device")
class TestForcedCegbDistributed:
    """Forced splits and CEGB under distributed learners (VERDICT r2 #4).

    The reference runs ForceSplits inside every learner
    (serial_tree_learner.cpp:459) and CEGB is per-split bookkeeping
    (cost_effective_gradient_boosting.hpp:23); both must produce the
    identical model under tree_learner=data as under serial."""

    def _data(self):
        r = np.random.RandomState(3)
        X = r.randn(4096, 6).astype(np.float32)
        y = (X[:, 0] + 0.5 * X[:, 1] +
             0.1 * r.randn(4096) > 0).astype(np.float32)
        return X, y

    def test_forced_splits_data_parallel_matches_serial(self, tmp_path):
        import json
        X, y = self._data()
        fn = tmp_path / "forced.json"
        fn.write_text(json.dumps(
            {"feature": 2, "threshold": 0.0,
             "left": {"feature": 3, "threshold": 0.5}}))
        params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "forcedsplits_filename": str(fn), "min_data_in_leaf": 5}
        bst_s = lgb.train(dict(params), lgb.Dataset(X, label=y), 5)
        bst_p = lgb.train(dict(params, tree_learner="data", num_devices=4),
                          lgb.Dataset(X, label=y), 5)
        # the forced structure must be present in the distributed model too
        for bst in (bst_s, bst_p):
            root = bst.dump_model()["tree_info"][0]["tree_structure"]
            assert root["split_feature"] == 2
            assert root["left_child"]["split_feature"] == 3
        np.testing.assert_allclose(bst_s.predict(X), bst_p.predict(X),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("cegb_params", [
        {"cegb_penalty_split": 0.1},
        {"cegb_tradeoff": 1.0,
         "cegb_penalty_feature_coupled": [0.0, 1e6, 0.0, 0.0, 0.0, 0.0]},
        {"cegb_penalty_feature_lazy": [0.5] * 6},
    ], ids=["split", "coupled", "lazy"])
    def test_cegb_data_parallel_matches_serial(self, cegb_params):
        X, y = self._data()
        params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  **cegb_params}
        bst_s = lgb.train(dict(params), lgb.Dataset(X, label=y), 5)
        bst_p = lgb.train(dict(params, tree_learner="data", num_devices=4),
                          lgb.Dataset(X, label=y), 5)
        np.testing.assert_allclose(bst_s.predict(X), bst_p.predict(X),
                                   rtol=1e-4, atol=1e-5)

    def test_cegb_feature_parallel_matches_serial(self):
        X, y = self._data()
        params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "cegb_penalty_feature_lazy": [0.5] * 6}
        bst_s = lgb.train(dict(params), lgb.Dataset(X, label=y), 5)
        bst_p = lgb.train(dict(params, tree_learner="feature",
                               num_devices=4),
                          lgb.Dataset(X, label=y), 5)
        np.testing.assert_allclose(bst_s.predict(X), bst_p.predict(X),
                                   rtol=1e-4, atol=1e-5)
