"""Distributed training subsystem: byte-parity oracles + unit tests.

The crossbar contract (distributed/crossbar.py + docs/Distributed.md):
`tree_learner=data` under the exact reduce-scatter histogram flavor
grows trees byte-identical to `tree_learner=serial` — on the 8-virtual-
device mesh the conftest provisions, and trivially on a 1-device mesh
(serial fallback). The oracles compare `model_to_string()` up to the
embedded parameter dump (the `tree_learner` line necessarily differs)
and run the per-iteration sharded path (`fused_block_size=1`): the
fused block is deterministic but carries a documented 1-ulp score-
rounding difference (distributed/fused.py).

Also under test here, by name, for the COLL004/FAULT001 manifests:
`build_feature_shards`, `reduce_scatter_hist`, `merge_streaming_sketch`
and the `distributed_hist_agg` fault site.

Row counts divide the 8-device mesh (row_pad=0) — parity with padding
is exercised at small scale by the 1-device fallback test.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.reliability.faults import InjectedFault, faults

pytestmark = [pytest.mark.distributed]

N, F = 800, 12          # divisible by 8: zero row padding on the mesh


def _make(task, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(N, F)
    if task == "regression":
        y = X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.1 * rng.randn(N)
        obj = "regression"
    elif task == "binary":
        y = (X[:, 0] + 0.5 * X[:, 1] ** 2 +
             0.3 * rng.randn(N) > 0.5).astype(np.float32)
        obj = "binary"
    else:
        centers = rng.randn(4, F) * 2
        d = ((X[:, None, :] - centers[None]) ** 2).sum(-1)
        y = d.argmin(1).astype(np.float32)
        obj = "multiclass"
    return X, y, obj


def _trees(bst):
    """Everything before the embedded parameter dump: the trees and
    learned state. `[tree_learner: ...]` in the dump differs by
    construction between the runs under comparison."""
    return bst.model_to_string().split("parameters:")[0]


def _train(task, extra, rounds=8):
    X, y, obj = _make(task)
    # enable_bundle=False keeps the crossbar's `auto` hist_agg on the
    # exact reduce-scatter flavor (EFB is a documented psum downgrade)
    params = {"objective": obj, "num_leaves": 15, "min_data_in_leaf": 5,
              "verbose": -1, "fused_block_size": 1,
              "enable_bundle": False, **extra}
    if obj == "multiclass":
        params["num_class"] = 4
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=rounds)
    return bst


# ---------------------------------------------------------------------------
# byte-parity oracles: serial vs the crossbar learners

def test_data_reduce_scatter_parity_regression():
    serial = _train("regression", {"tree_learner": "serial"})
    data = _train("regression", {"tree_learner": "data"})
    assert _trees(serial) == _trees(data)


@pytest.mark.slow
@pytest.mark.parametrize("task", ["binary", "multiclass"])
def test_data_reduce_scatter_parity_tasks(task):
    serial = _train(task, {"tree_learner": "serial"})
    data = _train(task, {"tree_learner": "data"})
    assert _trees(serial) == _trees(data)


def test_data_parity_one_device_mesh():
    # a 1-device mesh falls back to the serial learner (crossbar
    # downgrade): the model must be byte-identical, trivially
    serial = _train("regression", {"tree_learner": "serial"})
    data = _train("regression", {"tree_learner": "data",
                                 "num_devices": 1})
    assert _trees(serial) == _trees(data)


@pytest.mark.slow
def test_feature_parallel_parity():
    # each device scans its own feature partition with the serial
    # histogram order; the global argmax merge preserves byte parity
    # at this scale
    serial = _train("regression", {"tree_learner": "serial"})
    feat = _train("regression", {"tree_learner": "feature"})
    assert _trees(serial) == _trees(feat)


@pytest.mark.slow
def test_voting_parallel_full_cover_parity():
    # 2 * top_k >= F: every feature is vote-selected on every device,
    # so PV-Tree degrades to exact data-parallel aggregation
    serial = _train("regression", {"tree_learner": "serial"})
    vote = _train("regression", {"tree_learner": "voting", "top_k": 20})
    assert _trees(serial) == _trees(vote)


@pytest.mark.slow
def test_psum_flavor_is_numerically_close():
    # the psum fallback sums blocked partials: numerically (not
    # bitwise) equal to serial — predictions agree to float tolerance
    X, _, _ = _make("regression")
    serial = _train("regression", {"tree_learner": "serial"})
    psum = _train("regression", {"tree_learner": "data",
                                 "distributed_hist_agg": "psum"})
    np.testing.assert_allclose(serial.predict(X), psum.predict(X),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_fused_sharded_path_engages_and_is_deterministic():
    """The default engine posture (fused_block_size=10, pipeline=True)
    must dispatch through the sharded fused builder, and the result
    must not depend on block size or pipelining — the same-path
    determinism chaos resume replays."""
    from lightgbm_tpu.boosting import gbdt as G
    calls = {"n": 0}
    orig = G.GBDT._build_sharded_fused

    def spy(self):
        calls["n"] += 1
        return orig(self)

    G.GBDT._build_sharded_fused = spy
    try:
        m10 = _train("regression", {"tree_learner": "data",
                                    "fused_block_size": 10}, rounds=12)
        assert calls["n"] > 0, "sharded fused builder never engaged"
        m4 = _train("regression", {"tree_learner": "data",
                                   "fused_block_size": 4,
                                   "pipeline": False}, rounds=12)
        m10b = _train("regression", {"tree_learner": "data",
                                     "fused_block_size": 10}, rounds=12)
    finally:
        G.GBDT._build_sharded_fused = orig
    assert _trees(m10) == _trees(m10b)
    assert _trees(m10) == _trees(m4)


# ---------------------------------------------------------------------------
# unit tests: hist_agg + binning entry points, by name

def test_build_feature_shards_transposes_all_rows():
    import jax
    from lightgbm_tpu.distributed.hist_agg import (build_feature_shards,
                                                   feature_shard_width)
    from lightgbm_tpu.parallel import CommSpec, make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh(8)
    comm = CommSpec(axis="data", mode="data", num_devices=8,
                    hist_agg="reduce_scatter")
    rng = np.random.RandomState(3)
    bins = rng.randint(0, 17, size=(64, 10)).astype(np.int8)
    sharded = jax.device_put(bins, NamedSharding(mesh, P("data")))
    with mesh:
        bins_ft = build_feature_shards(mesh, comm, sharded)
    fp = feature_shard_width(10, 8)
    assert bins_ft.shape == (64, fp * 8)
    # device d's block holds ALL rows of features [d*fp, (d+1)*fp)
    got = np.concatenate(
        [np.asarray(s.data) for s in
         sorted(bins_ft.addressable_shards,
                key=lambda s: s.index[1].start or 0)],
        axis=1)
    want = np.pad(bins, ((0, 0), (0, fp * 8 - 10)))
    np.testing.assert_array_equal(got, want)


def test_reduce_scatter_hist_owns_summed_block():
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.distributed.hist_agg import reduce_scatter_hist
    from lightgbm_tpu.parallel import make_mesh
    from lightgbm_tpu.parallel.learner import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh(8)
    rng = np.random.RandomState(5)
    # per-device partial histograms [S=2, Fpad=16, B=4, 3]
    parts = rng.rand(8, 2, 16, 4, 3).astype(np.float32)

    import functools

    @functools.partial(shard_map, mesh=mesh, in_specs=(P("data"),),
                       out_specs=P("data"), check_vma=False)
    def run(p):
        return reduce_scatter_hist(p[0], "data")[None]

    out = np.asarray(jax.jit(run)(jnp.asarray(
        parts.reshape(8, 2, 16, 4, 3))))
    total = parts.sum(0)        # the global histogram
    for d in range(8):
        np.testing.assert_allclose(out[d], total[:, 2 * d:2 * (d + 1)],
                                   rtol=1e-6)


def test_merge_streaming_sketch_single_process_is_none():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.distributed.binning import (distributed_mapper_sync,
                                                  merge_streaming_sketch)
    assert merge_streaming_sketch is not None  # exported entry point
    cfg = Config({"verbose": -1})
    # single-process: the loader bins locally; distribution is over
    # devices only (rows shard after binning)
    assert distributed_mapper_sync(cfg, cat=None) is None


def test_distributed_sketch_telemetry():
    from lightgbm_tpu.observability import registry
    registry.enable()
    try:
        from lightgbm_tpu.distributed.binning import _record_sketch
        before = registry.distributed_snapshot()
        _record_sketch(123)
        snap = registry.distributed_snapshot()
        assert snap["sketch_rows"] == before["sketch_rows"] + 123
        assert snap["sketch_merges"] == before["sketch_merges"] + 1
    finally:
        registry.disable()


# ---------------------------------------------------------------------------
# fault site: distributed_hist_agg

def test_distributed_hist_agg_fault_site_fires():
    X, y, _ = _make("regression")
    faults.schedule("distributed_hist_agg", fail=1)
    try:
        with pytest.raises(InjectedFault, match="distributed_hist_agg"):
            lgb.train({"objective": "regression", "num_leaves": 7,
                       "verbose": -1, "tree_learner": "data",
                       "enable_bundle": False,
                       "distributed_hist_agg": "reduce_scatter"},
                      lgb.Dataset(X, label=y), num_boost_round=1)
    finally:
        faults.clear()


# ---------------------------------------------------------------------------
# provision_virtual_devices: one-shot latch ordering hazard

def test_provision_after_backend_touch_raises_clearly():
    """A harness that touches the backend before provisioning latches
    the device count; the provision call must fail loudly with the
    ordering diagnosis, not hand back a 1-device 'mesh'."""
    code = (
        "import jax\n"
        "jax.devices()          # latch a 1-device CPU backend\n"
        "from lightgbm_tpu.parallel.mesh import provision_virtual_devices\n"
        "try:\n"
        "    provision_virtual_devices(8)\n"
        "except RuntimeError as e:\n"
        "    assert 'before any other JAX use' in str(e) or \\\n"
        "        'provision_virtual_devices' in str(e), e\n"
        "    print('LATCH_ERROR_OK')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env.pop("XLA_FLAGS", None)   # no pre-provisioned virtual devices
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stderr
    assert "LATCH_ERROR_OK" in out.stdout, out.stdout + out.stderr


# ---------------------------------------------------------------------------
# chaos: rank death at the 8-device (2 ranks x 4 devices) geometry

@pytest.mark.slow
@pytest.mark.chaos
def test_rank_death_at_8_devices_resumes_byte_identical(tmp_path):
    """The distributed acceptance scenario: kill a rank mid-iteration
    out of the 8-device global mesh; the survivor aborts promptly and
    a coordinated-checkpoint resume finishes byte-identical to an
    unkilled reference run."""
    from lightgbm_tpu.reliability.faults import RANK_DEATH_EXIT_CODE
    from lightgbm_tpu.testing.chaos import (run_chaos_training,
                                            strip_rank_local_params)

    def model(workdir, rank):
        with open(os.path.join(workdir, f"model_{rank}.txt")) as f:
            return strip_rank_local_params(f.read())

    ref_dir = str(tmp_path / "ref")
    ref = run_chaos_training(
        ref_dir, rounds=8, ckpt_period=2,
        ckpt_dir=os.path.join(ref_dir, "ckpts"), timeout_s=30.0,
        devices_per_rank=4)
    for r in ref:
        assert r.returncode == 0, r.tail()
        assert "CHAOS_WORKER_DEVICES 8" in r.output, r.tail()
    ref_model = model(ref_dir, 0)

    chaos_dir = str(tmp_path / "chaos")
    chaos_ckpts = os.path.join(chaos_dir, "ckpts")
    res = {r.rank: r for r in run_chaos_training(
        chaos_dir, rounds=8, ckpt_period=2, ckpt_dir=chaos_ckpts,
        timeout_s=30.0, death_rank=1, death_iter=5,
        devices_per_rank=4)}
    assert res[1].returncode == RANK_DEATH_EXIT_CODE, res[1].tail()
    assert res[0].returncode not in (0, RANK_DEATH_EXIT_CODE), \
        res[0].tail()

    resume_dir = str(tmp_path / "resume")
    resumed = run_chaos_training(
        resume_dir, rounds=8, ckpt_period=2, ckpt_dir=chaos_ckpts,
        timeout_s=30.0, resume=True, devices_per_rank=4)
    for r in resumed:
        assert r.returncode == 0, r.tail()
    assert model(resume_dir, 0) == ref_model
    assert model(resume_dir, 1) == ref_model
