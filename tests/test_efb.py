"""EFB (exclusive feature bundling) tests — reference feature_group.h:25,
docs/Features.rst:36; implementation lightgbm_tpu/efb.py.

With conflict budget 0 and strictly-exclusive features the bundled
histogram expansion is EXACTLY the unbundled histogram, so training with
enable_bundle must reproduce the unbundled model bit-for-bit.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.efb import build_plan, bundle_matrix, make_device_tables


def make_exclusive(n=6000, groups=5, feats_per_group=8, seed=0):
    """Features arranged in groups where exactly one feature per group is
    non-zero per row — strictly exclusive within each group."""
    r = np.random.RandomState(seed)
    f = groups * feats_per_group
    X = np.zeros((n, f), np.float32)
    active = r.randint(0, feats_per_group, size=(n, groups))
    # low-cardinality values (like one-hot/count features, the EFB target
    # workload) so several features fit one <=256-bin bundle column
    vals = r.randint(1, 12, size=(n, groups)).astype(np.float32)
    for g in range(groups):
        X[np.arange(n), g * feats_per_group + active[:, g]] = vals[:, g]
    logit = X[:, 0] * 1.2 - X[:, 8] + 0.5 * X[:, 16] + 0.2 * r.randn(n)
    y = (logit > np.median(logit)).astype(np.float32)
    return X, y


def make_wide_sparse(n=20000, f=300, density=0.02, seed=1):
    r = np.random.RandomState(seed)
    X = np.zeros((n, f), np.float32)
    nnz_per_row = max(1, int(f * density))
    cols = r.randint(0, f, size=(n, nnz_per_row))
    X[np.arange(n)[:, None], cols] = \
        r.randint(1, 9, size=(n, nnz_per_row)).astype(np.float32)
    logit = X[:, :8].sum(axis=1) - X[:, 8:16].sum(axis=1) + \
        0.3 * r.randn(n)
    y = (logit > np.median(logit)).astype(np.float32)
    return X, y


class TestPlan:
    def test_bundles_exclusive_features(self):
        X, y = make_exclusive()
        ds = lgb.Dataset(X, label=y)
        ds.construct()
        b = ds.binned
        plan = build_plan(np.asarray(b.bins), b.num_bins, b.default_bins,
                          np.asarray(b.is_categorical))
        assert plan is not None and plan.effective
        # strictly exclusive groups compress heavily
        assert plan.num_cols < b.num_features / 2

    def test_no_plan_for_dense(self):
        r = np.random.RandomState(0)
        X = r.randn(3000, 12)
        y = (X[:, 0] > 0).astype(np.float32)
        ds = lgb.Dataset(X, label=y)
        ds.construct()
        b = ds.binned
        plan = build_plan(np.asarray(b.bins), b.num_bins, b.default_bins,
                          np.asarray(b.is_categorical))
        assert plan is None or not plan.effective

    def test_bundle_matrix_roundtrip(self):
        # every (row, feature) bin must be recoverable from the bundled
        # matrix: in-segment -> local bin, out-of-segment -> default bin
        X, y = make_exclusive(n=2000)
        ds = lgb.Dataset(X, label=y)
        ds.construct()
        b = ds.binned
        plan = build_plan(np.asarray(b.bins), b.num_bins, b.default_bins,
                          np.asarray(b.is_categorical))
        bund = bundle_matrix(np.asarray(b.bins), plan)
        assert bund.shape == (2000, plan.num_cols)
        bins = np.asarray(b.bins)
        for fi in range(b.num_features):
            g = plan.col_of_feat[fi]
            col = bund[:, g].astype(np.int64)
            in_seg = (col >= plan.seg_lo[fi]) & (col <= plan.seg_hi[fi])
            rec = np.where(in_seg, plan.local_of_pos[g][col],
                           b.default_bins[fi])
            np.testing.assert_array_equal(rec, bins[:, fi])


class TestHistogramExpansion:
    def test_expansion_matches_unbundled_histograms(self):
        """The sharp parity tool: expand(hist(bundled)) vs hist(unbundled).
        Non-default bins must be BIT-exact (same rows summed in the same
        order); the reconstructed default bin (total - segment_sum) is
        exact up to one f32 reassociation."""
        import jax.numpy as jnp
        from lightgbm_tpu.learner.histogram import build_histograms
        from lightgbm_tpu.efb import expand_histograms
        X, y = make_exclusive(n=3000)
        ds = lgb.Dataset(X, label=y)
        ds.construct()
        b = ds.binned
        bins = np.asarray(b.bins)
        plan = build_plan(bins, b.num_bins, b.default_bins,
                          np.asarray(b.is_categorical))
        assert plan is not None and plan.effective
        bund = bundle_matrix(bins, plan)
        efb = make_device_tables(plan, b.default_bins)
        r = np.random.RandomState(0)
        grad = jnp.asarray(r.randn(3000).astype(np.float32))
        hess = jnp.asarray(np.abs(r.randn(3000)).astype(np.float32))
        slot = jnp.asarray(r.randint(0, 4, 3000).astype(np.int32))
        cnt = jnp.ones(3000, jnp.float32)
        bmax = int(b.num_bins.max())
        h_ref = np.asarray(build_histograms(
            jnp.asarray(bins), grad, hess, slot, cnt, num_slots=4,
            bmax=bmax))
        h_b = build_histograms(
            jnp.asarray(bund), grad, hess, slot, cnt, num_slots=4,
            bmax=plan.bundle_bmax)
        h_exp = np.asarray(expand_histograms(h_b, efb))
        assert h_exp.shape == h_ref.shape
        dflt = np.zeros(h_ref.shape[:3], bool)
        for fi in range(b.num_features):
            if plan.is_multi[fi]:
                dflt[:, fi, b.default_bins[fi]] = True
        # bit-exact away from reconstructed default bins
        np.testing.assert_array_equal(h_exp[~dflt], h_ref[~dflt])
        np.testing.assert_allclose(h_exp[dflt], h_ref[dflt],
                                   rtol=1e-5, atol=1e-3)


class TestTrainingParity:
    PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "use_pallas": False}

    def _pair(self, X, y, extra=None, rounds=8):
        p = dict(self.PARAMS, **(extra or {}))
        b0 = lgb.train(dict(p, enable_bundle=False),
                       lgb.Dataset(X, label=y), rounds)
        b1 = lgb.train(dict(p, enable_bundle=True),
                       lgb.Dataset(X, label=y), rounds)
        return b0, b1

    def _assert_equivalent(self, b0, b1, X, y):
        # the reconstructed default-bin mass reassociates one f32 sum, so
        # near-tie splits may legitimately flip; the fitted function must
        # stay equivalent (first-tree structure IS exact: same grads,
        # histograms bit-equal away from the perturbed default bins)
        t0 = b0.dump_model()["tree_info"][0]["tree_structure"]
        t1 = b1.dump_model()["tree_info"][0]["tree_structure"]
        assert t0["split_feature"] == t1["split_feature"]
        p0, p1 = b0.predict(X), b1.predict(X)
        assert np.mean(np.abs(p0 - p1)) < 5e-3
        from lightgbm_tpu.metrics import AUCMetric
        w = np.ones(len(y))
        a0 = AUCMetric._auc_fast(p0, y > 0, w)
        a1 = AUCMetric._auc_fast(p1, y > 0, w)
        assert abs(a0 - a1) < 2e-3, (a0, a1)

    def test_model_parity_exclusive(self):
        X, y = make_exclusive()
        b0, b1 = self._pair(X, y)
        self._assert_equivalent(b0, b1, X, y)

    def test_parity_with_missing(self):
        X, y = make_exclusive()
        X[::17, 3] = np.nan
        b0, b1 = self._pair(X, y)
        self._assert_equivalent(b0, b1, X, y)

    def test_parity_data_parallel(self):
        import jax
        if len(jax.devices()) < 2:
            pytest.skip("needs multi-device")
        X, y = make_exclusive()
        b0, b1 = self._pair(X, y, extra={"tree_learner": "data",
                                         "num_devices": 4})
        self._assert_equivalent(b0, b1, X, y)

    def test_wide_sparse_auc_parity(self):
        # non-exclusive sparse data: bundling is approximate only through
        # the conflict budget (0 here -> still exact on the sample);
        # accuracy must match closely
        X, y = make_wide_sparse()
        b0, b1 = self._pair(X, y, rounds=15)
        from lightgbm_tpu.metrics import AUCMetric
        w = np.ones(len(y))
        a0 = AUCMetric._auc_fast(b0.predict(X), y > 0, w)
        a1 = AUCMetric._auc_fast(b1.predict(X), y > 0, w)
        assert a1 > a0 - 0.005, (a0, a1)

    def test_valid_set_eval_with_efb(self):
        X, y = make_exclusive()
        Xv, yv = make_exclusive(seed=7)
        hist = {}
        dtrain = lgb.Dataset(X, label=y)
        lgb.train(dict(self.PARAMS, enable_bundle=True), dtrain, 8,
                  valid_sets=[lgb.Dataset(Xv, label=yv,
                                          reference=dtrain)],
                  valid_names=["v"],
                  callbacks=[lgb.record_evaluation(hist)])
        assert "v" in hist and len(next(iter(hist["v"].values()))) == 8

    def test_dart_with_efb(self):
        # DART re-applies dropped trees to TRAIN scores through the
        # bundled bin matrix — routing must translate (regression for
        # the efb-less _tree_values call path)
        X, y = make_exclusive(n=3000)
        p = dict(self.PARAMS, boosting="dart", drop_rate=0.5)
        b0 = lgb.train(dict(p, enable_bundle=False),
                       lgb.Dataset(X, label=y), 10)
        b1 = lgb.train(dict(p, enable_bundle=True),
                       lgb.Dataset(X, label=y), 10)
        assert np.mean(np.abs(b0.predict(X) - b1.predict(X))) < 5e-3
