"""EFB on the MXU growth path (bundle-space kernels + device expansion).

Equality target: the portable scatter grower's EFB path (grower.py),
which is itself differentially tested against unbundled training in
test_efb.py. Interpret mode, runs on CPU.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # Pallas interpret mode

import jax.numpy as jnp

from lightgbm_tpu.data import BinnedDataset, Metadata
from lightgbm_tpu.efb import build_plan, bundle_matrix, make_device_tables
from lightgbm_tpu.learner.grower import grow_tree
from lightgbm_tpu.learner.grower_mxu import grow_tree_mxu
from lightgbm_tpu.learner.split import SplitHyperParams


def _sparse_ds(n=4000, f=24, seed=0, with_nan=False, with_cat=False,
               seg=False):
    rng = np.random.RandomState(seed)
    X = np.zeros((n, f))
    for g in range(0, f, 8):
        which = rng.randint(g, g + 8, size=n)
        X[np.arange(n), which] = rng.rand(n) + 0.5
    if with_cat:
        X[:, 3] = rng.randint(0, 6, size=n)  # dense categorical column
    if with_nan:
        X[rng.rand(n) < 0.05, 1] = np.nan
    logit = np.nan_to_num(X[:, 0]) * 2 + X[:, 8] - X[:, 16] + \
        0.3 * rng.randn(n)
    y = (logit > np.median(logit)).astype(np.float32)
    ds = BinnedDataset.from_raw(
        X, Metadata(n, label=y), max_bin=15,
        categorical_features=[3] if with_cat else None)
    plan = build_plan(np.asarray(ds.bins), ds.num_bins, ds.default_bins,
                      np.asarray(ds.is_categorical), max_bundle_bins=256)
    assert plan is not None and plan.effective
    # seg=True attaches the segmented-scan tables (split_bundled.py);
    # the MXU grower then scans bundle space directly
    efb = make_device_tables(
        plan, ds.default_bins,
        num_bins=ds.num_bins if seg else None,
        missing_is_nan=(ds.missing_types == 2) if seg else None,
        is_cat=np.asarray(ds.is_categorical) if seg else None)
    bund = jnp.asarray(bundle_matrix(np.asarray(ds.bins), plan))
    p = np.full(n, 0.5, np.float32)
    return ds, efb, bund, jnp.asarray(p - y), jnp.asarray(p * (1 - p))


def _grow_both(ds, efb, bund, g, h, num_leaves=15, **extra):
    cnt = jnp.ones(ds.num_data, jnp.float32)
    tail = (cnt, jnp.ones(ds.num_features, jnp.float32),
            jnp.asarray(ds.num_bins), jnp.asarray(ds.missing_types == 2),
            jnp.asarray(ds.is_categorical))
    kw = dict(num_leaves=num_leaves, max_depth=0,
              hp=SplitHyperParams(
                  min_data_in_leaf=20,
                  has_categorical=bool(np.any(ds.is_categorical))),
              bmax=int(ds.num_bins.max()))
    t_ref, r_ref = grow_tree(bund, g, h, *tail, leafwise=False,
                             efb=efb, **kw)
    t_mxu, r_mxu = grow_tree_mxu(bund, g, h, *tail, interpret=True,
                                 efb=efb, **extra, **kw)
    return t_ref, r_ref, t_mxu, r_mxu


def _assert_same_tree(t_ref, r_ref, t_mxu, r_mxu):
    assert int(t_ref.num_leaves) == int(t_mxu.num_leaves)
    nn = int(t_ref.num_nodes)
    for fld in ("split_feature", "threshold_bin", "left", "right",
                "is_cat", "default_left"):
        np.testing.assert_array_equal(
            np.asarray(getattr(t_ref, fld))[:nn],
            np.asarray(getattr(t_mxu, fld))[:nn], err_msg=fld)
    np.testing.assert_allclose(np.asarray(t_ref.leaf_value)[:nn],
                               np.asarray(t_mxu.leaf_value)[:nn],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(r_ref), np.asarray(r_mxu))


class TestEfbMXU:
    @pytest.mark.parametrize("seg", [False, True])
    def test_matches_scatter_efb(self, seg):
        ds, efb, bund, g, h = _sparse_ds(seg=seg)
        _assert_same_tree(*_grow_both(ds, efb, bund, g, h))

    @pytest.mark.parametrize("seg", [False, True])
    def test_matches_with_nan(self, seg):
        ds, efb, bund, g, h = _sparse_ds(seed=1, with_nan=True, seg=seg)
        _assert_same_tree(*_grow_both(ds, efb, bund, g, h))

    @pytest.mark.parametrize("seg", [False, True])
    def test_matches_with_categorical(self, seg):
        ds, efb, bund, g, h = _sparse_ds(seed=2, with_cat=True, seg=seg)
        _assert_same_tree(*_grow_both(ds, efb, bund, g, h))

    def test_overgrow_prune_with_efb(self):
        # mirror of test_mxu_kernels overshoot checks: the pruned tree
        # must be self-consistent (row_node == routing fresh rows
        # through it, via the bundle translation tables) and reach the
        # leaf budget; exact structural parity vs batched growth is not
        # expected (different growth order by design)
        from lightgbm_tpu.learner.predict import predict_binned_tree
        ds, efb, bund, g, h = _sparse_ds(seed=3)
        cnt = jnp.ones(ds.num_data, jnp.float32)
        tail = (cnt, jnp.ones(ds.num_features, jnp.float32),
                jnp.asarray(ds.num_bins),
                jnp.asarray(ds.missing_types == 2),
                jnp.asarray(ds.is_categorical))
        t, r = grow_tree_mxu(bund, g, h, *tail, num_leaves=15,
                             max_depth=0,
                             hp=SplitHyperParams(min_data_in_leaf=20),
                             bmax=int(ds.num_bins.max()), interpret=True,
                             overshoot=2.0, efb=efb)
        assert int(t.num_leaves) == 15
        vals_route = predict_binned_tree(
            t, bund, jnp.asarray(ds.num_bins),
            jnp.asarray(ds.missing_types == 2), efb)
        vals_rows = np.asarray(t.leaf_value)[np.asarray(r)]
        np.testing.assert_allclose(np.asarray(vals_route), vals_rows,
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("with_nan,with_cat", [(False, False),
                                                   (True, False),
                                                   (True, True)])
    def test_segmented_scan_matches_expansion(self, with_nan, with_cat):
        # scan-level differential: find_best_splits_bundled on [S,Fb,Bb]
        # must pick the same split as expand_histograms +
        # find_best_splits on [S,F,Bmax], per slot, on histograms built
        # from real routed rows
        import jax
        from lightgbm_tpu.efb import expand_histograms
        from lightgbm_tpu.learner.split import find_best_splits
        from lightgbm_tpu.learner.split_bundled import \
            find_best_splits_bundled
        ds, efb, bund, g, h = _sparse_ds(seed=7, with_nan=with_nan,
                                         with_cat=with_cat, seg=True)
        n = ds.num_data
        s = 4
        rng = np.random.RandomState(3)
        row_node = jnp.asarray(rng.randint(0, s, n))
        fb, bb = efb.num_cols, efb.bundle_bmax
        onehot_s = jax.nn.one_hot(row_node, s, dtype=jnp.float32)
        onehot_b = jax.nn.one_hot(np.asarray(bund), bb, dtype=jnp.float32)
        stats = jnp.stack([g, h, jnp.ones(n, jnp.float32)], -1)
        hist_b = jnp.einsum("ns,nfb,nc->sfbc", onehot_s, onehot_b, stats)
        pg = jnp.einsum("ns,n->s", onehot_s, g)
        ph = jnp.einsum("ns,n->s", onehot_s, h)
        pc = jnp.sum(onehot_s, axis=0)
        po = jnp.zeros(s)
        nb = jnp.asarray(ds.num_bins)
        mn = jnp.asarray(ds.missing_types == 2)
        ic = jnp.asarray(ds.is_categorical)
        fm = jnp.ones(ds.num_features, jnp.float32)
        hp = SplitHyperParams(
            min_data_in_leaf=5,
            has_categorical=bool(np.any(ds.is_categorical)))
        bs_seg = find_best_splits_bundled(hist_b, pg, ph, pc, po, nb, mn,
                                          ic, fm, hp, efb)
        bs_exp = find_best_splits(expand_histograms(hist_b, efb), pg, ph,
                                  pc, po, nb, mn, ic, fm, hp)
        np.testing.assert_array_equal(np.asarray(bs_seg.feature),
                                      np.asarray(bs_exp.feature))
        np.testing.assert_array_equal(np.asarray(bs_seg.threshold_bin),
                                      np.asarray(bs_exp.threshold_bin))
        np.testing.assert_array_equal(np.asarray(bs_seg.default_left),
                                      np.asarray(bs_exp.default_left))
        np.testing.assert_allclose(np.asarray(bs_seg.gain),
                                   np.asarray(bs_exp.gain),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(bs_seg.left_count),
                                   np.asarray(bs_exp.left_count),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(bs_seg.left_output),
                                   np.asarray(bs_exp.left_output),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(bs_seg.cat_bitset),
                                      np.asarray(bs_exp.cat_bitset))

    def test_sharded_efb_mxu_matches_serial(self):
        # EFB rides the data-parallel MXU grower since round 4
        # (gbdt._mxu_exclusions): bundle-space histograms psum across
        # shards, segmented scan on the global sums — tree-identical to
        # the serial MXU grower
        import jax
        from lightgbm_tpu.parallel import CommSpec, make_mesh
        from lightgbm_tpu.parallel.learner import make_sharded_grower
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        ds, efb, bund, g, h = _sparse_ds(n=4096, seg=True)
        cnt = jnp.ones(ds.num_data, jnp.float32)
        args = (bund, g, h, cnt,
                jnp.ones(ds.num_features, jnp.float32),
                jnp.asarray(ds.num_bins),
                jnp.asarray(ds.missing_types == 2),
                jnp.asarray(ds.is_categorical))
        kw = dict(num_leaves=15, max_depth=0,
                  hp=SplitHyperParams(min_data_in_leaf=20),
                  bmax=int(ds.num_bins.max()))
        t_s, rn_s = grow_tree_mxu(*args, interpret=True, efb=efb, **kw)
        mesh = make_mesh(4)
        comm = CommSpec(axis="data", mode="data", num_devices=4)
        grower = make_sharded_grower(
            mesh, comm, leafwise=False, use_mxu=True, interpret=True,
            efb=efb, max_depth=0, num_leaves=15,
            hp=SplitHyperParams(min_data_in_leaf=20),
            bmax=int(ds.num_bins.max()))
        with mesh:
            t_p, rn_p = grower(*args)
        _assert_same_tree(t_s, rn_s, t_p, rn_p)

    def test_quantized_with_efb(self):
        ds, efb, bund, g, h = _sparse_ds(seed=4)
        cnt = jnp.ones(ds.num_data, jnp.float32)
        tail = (cnt, jnp.ones(ds.num_features, jnp.float32),
                jnp.asarray(ds.num_bins),
                jnp.asarray(ds.missing_types == 2),
                jnp.asarray(ds.is_categorical))
        kw = dict(num_leaves=15, max_depth=0,
                  hp=SplitHyperParams(min_data_in_leaf=20),
                  bmax=int(ds.num_bins.max()), interpret=True, efb=efb)
        import jax
        t, r = grow_tree_mxu(bund, g, h, *tail, quantized_grad=True,
                             rng_key=jax.random.PRNGKey(0),
                             overshoot=2.0, **kw)
        # quantization perturbs only the search; leaf values are refit
        # exactly — check the tree is sane and refit sums add up
        assert int(t.num_leaves) >= 4
        lf = np.asarray(t.is_leaf)
        np.testing.assert_allclose(
            np.asarray(t.count)[lf].sum(), ds.num_data, rtol=1e-6)
