"""Elastic world-resize tests (docs/Distributed.md "Elasticity").

Fast tier (no subprocesses, tier-1): the membership-epoch state
machine, stale-epoch rejection, the reshard loader's W -> W' -> W
byte-identity, the heartbeat-directory shrink vote, the watchdog's
propose-shrink-then-fall-back abort path, decorrelated backoff jitter,
the lightgbm_tpu_membership registry family and the regression
sentinel's chaos_resize block.

Slow tier (`make elastic`): the shrink-and-finish reincarnation
scenario — a rank killed mid-iteration at the 2-rank x 4-device
geometry, survivors vote a new epoch and exit 75 (never 113), the
supervisor relaunches them at the shrunken world, and the finished
model is byte-identical to a fixed-world run resumed from the same
epoch checkpoint.
"""

import json
import os

import numpy as np
import pytest

from lightgbm_tpu.distributed import elastic
from lightgbm_tpu.observability.registry import registry
from lightgbm_tpu.reliability.backoff import BackoffPolicy
from lightgbm_tpu.reliability.checkpoint import (
    COMMIT_MARKER, FORMAT_VERSION, bundle_world, load_checkpoint_resharded)
from lightgbm_tpu.reliability.faults import (KNOWN_SITES,
                                             InjectedFault, faults)
from lightgbm_tpu.reliability.watchdog import (CollectiveGuard,
                                               write_heartbeat)
from lightgbm_tpu.utils.log import LightGBMError

pytestmark = pytest.mark.elastic


@pytest.fixture(autouse=True)
def _fresh_epoch(monkeypatch):
    monkeypatch.delenv("LIGHTGBM_TPU_EPOCH", raising=False)
    elastic.reset_epoch()
    yield
    elastic.reset_epoch()


# ----------------------------------------------------------------------
# membership-epoch state + stale-epoch rejection

def test_epoch_defaults_to_zero_and_is_settable():
    assert elastic.current_epoch() == 0
    elastic.set_epoch(3)
    assert elastic.current_epoch() == 3


def test_epoch_seeded_from_supervisor_env(monkeypatch):
    monkeypatch.setenv("LIGHTGBM_TPU_EPOCH", "7")
    elastic.reset_epoch()
    assert elastic.current_epoch() == 7


def test_epoch_agreement_accepts_uniform_epochs():
    elastic.set_epoch(2)
    elastic.check_epoch_agreement([2, 2, 2], "unit")


def test_epoch_agreement_rejects_span():
    elastic.set_epoch(2)
    with pytest.raises(LightGBMError, match="span membership epochs"):
        elastic.check_epoch_agreement([1, 2], "unit")


def test_epoch_agreement_rejects_foreign_epoch():
    elastic.set_epoch(2)
    with pytest.raises(LightGBMError, match="does not match"):
        elastic.check_epoch_agreement([1, 1], "unit")


def test_epoch_agree_single_process():
    elastic.set_epoch(5)
    assert elastic.epoch_agree() == 5


def test_guarded_allgather_carries_epoch_single_process():
    # the piggybacked epoch round-trips the wire and agrees with the
    # local epoch — the rank-uniform fast path of stale-epoch rejection
    from lightgbm_tpu.parallel.comm import guarded_allgather
    elastic.set_epoch(4)
    out = guarded_allgather(np.arange(3), label="elastic_unit")
    np.testing.assert_array_equal(np.asarray(out).reshape(-1),
                                  [0, 1, 2])


# ----------------------------------------------------------------------
# reshard: offsets, slicing, and the topology-flexible loader

def test_reshard_offsets_single_process():
    assert elastic.reshard_offsets(17) == (0, 17)


def test_reshard_slice_partitions_rows_and_keeps_rng_key():
    rng_key = np.asarray([7, 9], dtype=np.uint32)
    arrays = {"train_score": np.arange(10, dtype=np.float32),
              "bag_mask": np.arange(10) % 2 == 0,
              "rng_key": rng_key}
    lo = elastic.reshard_slice(arrays, 0, 6, 10)
    hi = elastic.reshard_slice(arrays, 6, 4, 10)
    np.testing.assert_array_equal(lo["train_score"], np.arange(6))
    np.testing.assert_array_equal(hi["train_score"], np.arange(6, 10))
    assert lo["train_score"].shape[0] + hi["train_score"].shape[0] == 10
    np.testing.assert_array_equal(lo["rng_key"], rng_key)
    np.testing.assert_array_equal(hi["rng_key"], rng_key)


def _write_world2_bundle(ckpt_dir, iteration=4, rows=(6, 4)):
    """A committed 2-rank coordinated bundle with row-partitioned
    arrays; returns (bundle_path, per-rank array dicts)."""
    bundle = os.path.join(ckpt_dir, f"ckpt_{iteration:07d}")
    os.makedirs(bundle, exist_ok=True)
    with open(os.path.join(bundle, "model.txt"), "w") as f:
        f.write("tree\nend of trees\n")
    with open(os.path.join(bundle, "state.json"), "w") as f:
        json.dump({"format_version": FORMAT_VERSION,
                   "iteration": iteration, "world_size": 2}, f)
    shards = []
    offset = 0
    rng_key = np.asarray([11, 13], dtype=np.uint32)
    for r, n in enumerate(rows):
        arrs = {"train_score": np.arange(offset, offset + n,
                                         dtype=np.float32),
                "bag_mask": (np.arange(offset, offset + n) % 3 == 0),
                "rng_key": rng_key}
        np.savez(os.path.join(bundle, f"shard_{r:03d}.npz"), **arrs)
        shards.append(arrs)
        offset += n
    with open(os.path.join(bundle, COMMIT_MARKER), "w") as f:
        f.write("ok\n")
    return bundle, shards


def test_bundle_world_probe(tmp_path):
    assert bundle_world(str(tmp_path / "nope")) is None
    ckpt_dir = str(tmp_path / "ck")
    _write_world2_bundle(ckpt_dir)
    assert bundle_world(ckpt_dir) == 2


def test_reshard_loader_roundtrip_is_byte_identical(tmp_path):
    # W=2 bundle -> W'=1 global load -> sliced back into W=2 blocks:
    # every byte of the original shards must come back
    ckpt_dir = str(tmp_path / "ck")
    bundle, shards = _write_world2_bundle(ckpt_dir, rows=(6, 4))
    st = load_checkpoint_resharded(ckpt_dir)
    assert st.iteration == 4
    assert st.state["resharded_from_world"] == 2
    assert st.state["reshard_total_rows"] == 10
    assert st.state["reshard_rows_per_rank"] == [6, 4]
    np.testing.assert_array_equal(st.arrays["train_score"],
                                  np.arange(10, dtype=np.float32))
    np.testing.assert_array_equal(st.arrays["rng_key"],
                                  shards[0]["rng_key"])
    offset = 0
    for r, orig in enumerate(shards):
        n = orig["train_score"].shape[0]
        back = elastic.reshard_slice(st.arrays, offset, n, 10)
        for key in orig:
            assert back[key].tobytes() == orig[key].tobytes(), \
                f"shard {r} key {key} not byte-identical"
        offset += n


def test_reshard_loader_rejects_missing_shard(tmp_path):
    ckpt_dir = str(tmp_path / "ck")
    bundle, _ = _write_world2_bundle(ckpt_dir)
    os.unlink(os.path.join(bundle, "shard_001.npz"))
    # a missing shard also un-commits the bundle for latest_checkpoint?
    # no — COMMIT is still present; the loader must name the tear
    with pytest.raises(LightGBMError, match="shard_001"):
        load_checkpoint_resharded(ckpt_dir)


def test_reshard_loader_counts_in_membership_metrics(tmp_path):
    registry.reset()
    ckpt_dir = str(tmp_path / "ck")
    _write_world2_bundle(ckpt_dir)
    load_checkpoint_resharded(ckpt_dir)
    snap = registry.membership_snapshot()
    assert snap["resharded_loads"] == 1
    assert snap["reshard_wall_s"] >= 0.0


# ----------------------------------------------------------------------
# the heartbeat-directory shrink vote

def _stamp(hb_dir, rank, when):
    write_heartbeat(hb_dir, rank, when)


def test_plan_resize_names_survivors_dead_and_joiners(tmp_path):
    hb = str(tmp_path / "hb")
    now = 1000.0
    _stamp(hb, 0, now - 0.1)        # fresh (self anyway)
    _stamp(hb, 1, now - 60.0)       # stale -> dead
    # rank 2 never heartbeat -> dead
    elastic.request_join(hb, "replacement-a", now=now)
    survivors, dead, joiners = elastic.plan_resize(
        hb, rank=0, world=3, stale_after_s=3.0, now=now)
    assert survivors == [0]
    assert dead == [1, 2]
    assert joiners == ["replacement-a"]


def test_propose_shrink_single_survivor_commits(tmp_path):
    registry.reset()
    hb = str(tmp_path / "hb")
    now = 1000.0
    _stamp(hb, 0, now)
    _stamp(hb, 1, now - 60.0)
    rec = elastic.propose_shrink(
        hb, rank=0, world=2, epoch=0, min_world=1, timeout_s=5.0,
        stale_after_s=3.0, reason="unit", resume_bundle="/b",
        wall=lambda: now, sleep=lambda s: None)
    assert rec is not None
    assert (rec.epoch, rec.world, rec.members) == (1, 1, (0,))
    assert rec.resume_bundle == "/b"
    # committed record is durable and re-readable
    back = elastic.load_membership(hb)
    assert back == rec
    assert back.new_rank(0) == 0 and back.new_rank(1) is None
    snap = registry.membership_snapshot()
    assert snap["resizes"] == 1 and snap["shrinks"] == 1
    assert (snap["epoch"], snap["world"]) == (1, 1)


def test_propose_shrink_two_survivors_agree(tmp_path):
    hb = str(tmp_path / "hb")
    now = 1000.0
    _stamp(hb, 0, now)
    _stamp(hb, 1, now)
    _stamp(hb, 2, now - 60.0)       # the dead one
    # peer rank 1's agreeing proposal is already on disk
    elastic._write_json_atomic(
        elastic._proposal_path(hb, 1, 1),
        {"epoch": 1, "from_rank": 1, "old_world": 3,
         "members": [0, 1], "joiners": [], "stamp": now})
    rec = elastic.propose_shrink(
        hb, rank=0, world=3, epoch=0, timeout_s=5.0, stale_after_s=3.0,
        wall=lambda: now, sleep=lambda s: None)
    assert rec is not None
    assert (rec.world, rec.members) == (2, (0, 1))
    # rank 1 (not the committer) verifies the same record
    rec1 = elastic.propose_shrink(
        hb, rank=1, world=3, epoch=0, timeout_s=5.0, stale_after_s=3.0,
        wall=lambda: now, sleep=lambda s: None)
    assert rec1 == rec


def test_propose_shrink_admits_parked_joiner(tmp_path):
    hb = str(tmp_path / "hb")
    now = 1000.0
    _stamp(hb, 0, now)
    _stamp(hb, 1, now - 60.0)
    elastic.request_join(hb, "newbie", now=now)
    rec = elastic.propose_shrink(
        hb, rank=0, world=2, epoch=0, timeout_s=5.0, stale_after_s=3.0,
        wall=lambda: now, sleep=lambda s: None)
    assert rec is not None
    assert rec.world == 2           # 1 survivor + 1 joiner
    assert rec.joiners == ("newbie",)


def test_propose_shrink_refuses_when_nobody_died(tmp_path):
    hb = str(tmp_path / "hb")
    now = 1000.0
    _stamp(hb, 0, now)
    _stamp(hb, 1, now - 0.5)        # fresh: wedged, not dead
    assert elastic.propose_shrink(
        hb, rank=0, world=2, epoch=0, timeout_s=5.0, stale_after_s=3.0,
        wall=lambda: now, sleep=lambda s: None) is None


def test_propose_shrink_respects_min_world(tmp_path):
    hb = str(tmp_path / "hb")
    now = 1000.0
    _stamp(hb, 0, now)
    _stamp(hb, 1, now - 60.0)
    assert elastic.propose_shrink(
        hb, rank=0, world=2, epoch=0, min_world=2, timeout_s=5.0,
        stale_after_s=3.0, wall=lambda: now,
        sleep=lambda s: None) is None


def test_propose_shrink_aborts_on_disagreement(tmp_path):
    hb = str(tmp_path / "hb")
    now = 1000.0
    _stamp(hb, 0, now)
    _stamp(hb, 1, now)
    _stamp(hb, 2, now - 60.0)
    elastic._write_json_atomic(
        elastic._proposal_path(hb, 1, 1),
        {"epoch": 1, "from_rank": 1, "old_world": 3,
         "members": [1], "joiners": [], "stamp": now})   # disagrees
    assert elastic.propose_shrink(
        hb, rank=0, world=3, epoch=0, timeout_s=5.0, stale_after_s=3.0,
        wall=lambda: now, sleep=lambda s: None) is None


def test_propose_shrink_times_out_waiting_for_peer(tmp_path):
    hb = str(tmp_path / "hb")
    start = 1000.0
    _stamp(hb, 0, start)
    _stamp(hb, 1, start)
    _stamp(hb, 2, start - 60.0)
    clock = {"t": start}

    def wall():
        return clock["t"]

    def sleep(s):
        clock["t"] += 1.0           # advance past the deadline quickly

    assert elastic.propose_shrink(
        hb, rank=0, world=3, epoch=0, timeout_s=2.0, stale_after_s=3.0,
        wall=wall, sleep=sleep) is None


def test_propose_shrink_carries_fault_site(tmp_path):
    hb = str(tmp_path / "hb")
    _stamp(hb, 0, 1000.0)
    _stamp(hb, 1, 940.0)
    assert "elastic_resize" in KNOWN_SITES
    faults.schedule("elastic_resize", fail=1)
    try:
        with pytest.raises(InjectedFault):
            elastic.propose_shrink(
                hb, rank=0, world=2, epoch=0, timeout_s=5.0,
                stale_after_s=3.0, wall=lambda: 1000.0,
                sleep=lambda s: None)
    finally:
        faults.clear()


# ----------------------------------------------------------------------
# epoch-file hygiene

def test_sweep_stale_epoch_files(tmp_path):
    hb = str(tmp_path / "hb")
    now = 1000.0
    _stamp(hb, 0, now)
    _stamp(hb, 3, now)                     # rank beyond the new world
    elastic._write_json_atomic(            # consumed proposal
        elastic._proposal_path(hb, 1, 0),
        {"epoch": 1, "members": [0]})
    elastic._write_json_atomic(            # future proposal survives
        elastic._proposal_path(hb, 2, 0),
        {"epoch": 2, "members": [0]})
    elastic._write_json_atomic(            # committed history survives
        elastic._member_path(hb, 1),
        {"epoch": 1, "world": 1, "members": [0]})
    elastic.sweep_stale_epoch_files(hb, epoch=1, world=2)
    names = sorted(os.listdir(hb))
    assert "hb_rank_000" in names
    assert "hb_rank_003" not in names
    assert os.path.basename(elastic._proposal_path(hb, 1, 0)) \
        not in names
    assert os.path.basename(elastic._proposal_path(hb, 2, 0)) in names
    assert os.path.basename(elastic._member_path(hb, 1)) in names


def test_configure_watchdog_sweeps_on_rearm(tmp_path):
    from lightgbm_tpu.reliability.watchdog import (configure_watchdog,
                                                   shutdown_watchdog)
    hb = str(tmp_path / "hb")
    _stamp(hb, 0, 1000.0)
    _stamp(hb, 5, 1000.0)                  # ghost of the bigger world
    try:
        configure_watchdog(5.0, rank=0, world=2, heartbeat_dir=hb,
                           interval_s=0.25, abort_fn=lambda d: None)
        assert not os.path.exists(os.path.join(hb, "hb_rank_005"))
    finally:
        shutdown_watchdog()


# ----------------------------------------------------------------------
# the watchdog abort path: propose-shrink, fall back to abort

def _make_guard(hb, *, elastic_cfg, aborts, now=1000.0):
    return CollectiveGuard(
        5.0, rank=0, world=2, heartbeat_dir=hb,
        heartbeat_interval_s=0.25, wall=lambda: now,
        abort_fn=aborts.append, elastic=elastic_cfg)


def test_watchdog_abort_becomes_resize_when_elastic(tmp_path):
    registry.reset()
    hb = str(tmp_path / "hb")
    now = 1000.0
    _stamp(hb, 0, now)
    _stamp(hb, 1, now - 60.0)
    aborts = []
    g = _make_guard(hb, elastic_cfg={"min_world": 1,
                                     "epoch_timeout_s": 5.0,
                                     "ckpt_dir": ""},
                    aborts=aborts, now=now)
    g._abort("rank 1 last seen 60.0s ago")
    assert len(aborts) == 1
    assert aborts[0].startswith("elastic_resize epoch=1 world=1")
    assert elastic.load_membership(hb).world == 1
    # the resize path must NOT count as a watchdog abort
    assert registry.collective_snapshot()["aborts"] == 0


def test_watchdog_abort_unchanged_when_elastic_off(tmp_path):
    registry.reset()
    hb = str(tmp_path / "hb")
    now = 1000.0
    _stamp(hb, 0, now)
    _stamp(hb, 1, now - 60.0)
    aborts = []
    g = _make_guard(hb, elastic_cfg=None, aborts=aborts, now=now)
    g._abort("rank 1 last seen 60.0s ago")
    assert aborts == ["rank 1 last seen 60.0s ago"]
    assert elastic.load_membership(hb) is None        # no vote ran
    assert registry.collective_snapshot()["aborts"] == 1


def test_watchdog_falls_back_to_abort_when_vote_fails(tmp_path):
    # all peers fresh -> propose_shrink returns None -> plain abort
    registry.reset()
    hb = str(tmp_path / "hb")
    now = 1000.0
    _stamp(hb, 0, now)
    _stamp(hb, 1, now - 0.1)
    aborts = []
    g = _make_guard(hb, elastic_cfg={"min_world": 1,
                                     "epoch_timeout_s": 5.0,
                                     "ckpt_dir": ""},
                    aborts=aborts, now=now)
    g._abort("wedged interconnect")
    assert aborts == ["wedged interconnect"]
    assert registry.collective_snapshot()["aborts"] == 1


def test_watchdog_falls_back_when_resize_site_injected(tmp_path):
    registry.reset()
    hb = str(tmp_path / "hb")
    now = 1000.0
    _stamp(hb, 0, now)
    _stamp(hb, 1, now - 60.0)
    aborts = []
    g = _make_guard(hb, elastic_cfg={"min_world": 1,
                                     "epoch_timeout_s": 5.0,
                                     "ckpt_dir": ""},
                    aborts=aborts, now=now)
    faults.schedule("elastic_resize", fail=1)
    try:
        g._abort("rank 1 last seen 60.0s ago")
    finally:
        faults.clear()
    assert aborts == ["rank 1 last seen 60.0s ago"]   # plain abort
    assert registry.collective_snapshot()["aborts"] == 1


# ----------------------------------------------------------------------
# observability: the lightgbm_tpu_membership family

def test_membership_registry_family():
    registry.reset()
    registry.record_membership(2, 3)
    registry.record_membership_resize("shrink", 3, 2, joined=1)
    registry.record_membership_reshard(0.25)
    snap = registry.membership_snapshot()
    assert snap == {"epoch": 3, "world": 2, "resizes": 1, "shrinks": 1,
                    "joins": 1, "reshard_wall_s": 0.25,
                    "resharded_loads": 1}
    text = registry.prometheus_text()
    assert "lightgbm_tpu_membership_epoch 3" in text
    assert "lightgbm_tpu_membership_world 2" in text
    registry.reset()
    assert registry.membership_snapshot()["resizes"] == 0


# ----------------------------------------------------------------------
# satellite: decorrelated backoff jitter

def test_backoff_default_curve_is_unchanged():
    p = BackoffPolicy(base_ms=50.0, max_ms=2000.0)
    assert [p.delay_ms(a) for a in range(7)] == \
        [50.0, 100.0, 200.0, 400.0, 800.0, 1600.0, 2000.0]


def test_backoff_decorrelated_jitter_bounds_and_determinism():
    kw = dict(base_ms=50.0, max_ms=2000.0, jitter="decorrelated",
              seed=42)
    a = BackoffPolicy(**kw)
    b = BackoffPolicy(**kw)
    seq_a = [a.delay_ms(i) for i in range(64)]
    seq_b = [b.delay_ms(i) for i in range(64)]
    assert seq_a == seq_b                        # seeded: deterministic
    prev = 50.0
    for d in seq_a:
        # curve bounds: base <= d <= min(max, 3*prev)
        assert 50.0 <= d <= 2000.0
        assert d <= max(50.0, 3.0 * prev) + 1e-9
        prev = d
    assert len(set(seq_a)) > 8                   # actually jittered
    # different seeds decorrelate (the point of the exercise)
    c = BackoffPolicy(base_ms=50.0, max_ms=2000.0,
                      jitter="decorrelated", seed=43)
    assert [c.delay_ms(i) for i in range(64)] != seq_a
    # reset() restarts the ladder reproducibly-shaped
    a.reset()
    assert a.delay_ms(0) >= 50.0


def test_backoff_rejects_unknown_jitter():
    with pytest.raises(ValueError, match="jitter"):
        BackoffPolicy(jitter="full")


def test_backoff_wait_sleeps_jittered_delay():
    slept = []
    p = BackoffPolicy(base_ms=50.0, max_ms=2000.0, sleep=slept.append,
                      jitter="decorrelated", seed=7)
    d = p.wait(0)
    assert slept == [d / 1e3]


# ----------------------------------------------------------------------
# satellite: the regression sentinel's chaos_resize block

def test_regress_validates_chaos_resize_block():
    from lightgbm_tpu.observability.regress import validate_record
    rec = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
           "trees_per_sec": 10.0, "vs_baseline": 1.0,
           "tree_learner": "data",
           "chaos_resize": {"resizes": 1, "reshard_wall_s": 0.5,
                            "post_resize_trees_per_sec": 9.0}}
    assert validate_record("multichip", "MULTICHIP_r07.json", rec) == []
    bad = dict(rec, chaos_resize={"resizes": "one"})
    problems = validate_record("multichip", "MULTICHIP_r07.json", bad)
    assert any("chaos_resize" in p for p in problems)
    worse = dict(rec, chaos_resize=17)
    assert any("chaos_resize" in p for p in
               validate_record("multichip", "MULTICHIP_r07.json", worse))


def test_regress_tracks_post_resize_series():
    from lightgbm_tpu.observability.regress import _multichip_points
    records = [
        (6, "MULTICHIP_r06.json",
         {"rc": 0, "skipped": False, "trees_per_sec": 10.0}),
        (7, "MULTICHIP_r07.json",
         {"rc": 0, "skipped": False, "trees_per_sec": 11.0,
          "chaos_resize": {"resizes": 1, "reshard_wall_s": 0.5,
                           "post_resize_trees_per_sec": 9.0}}),
    ]
    series = _multichip_points(records)
    assert series["multichip_trees_per_sec"] == [(6, 10.0), (7, 11.0)]
    assert series["multichip_post_resize_trees_per_sec"] == [(7, 9.0)]
    assert series["multichip_reshard_inv_wall"] == [(7, 2.0)]


# ----------------------------------------------------------------------
# the slow acceptance scenario: shrink-and-finish, byte-identical to a
# fixed-world resume from the same epoch checkpoint

ROUNDS = 8
CKPT_PERIOD = 2
TIMEOUT_S = 30.0
DEATH_ITER = 5          # last coordinated commit lands at iteration 4


@pytest.mark.slow
def test_shrink_and_finish_matches_fixed_world_resume(tmp_path):
    from lightgbm_tpu.testing.chaos import (run_chaos_training,
                                            run_elastic_training,
                                            strip_rank_local_params)
    workdir = str(tmp_path / "elastic")
    ckpt_dir = os.path.join(workdir, "ckpts")
    out = run_elastic_training(
        workdir, rounds=ROUNDS, ckpt_period=CKPT_PERIOD,
        ckpt_dir=ckpt_dir, timeout_s=TIMEOUT_S, death_rank=1,
        death_iter=DEATH_ITER, world=2)

    # --- the resize happened, with ZERO aborts ----------------------
    rec = out["record"]
    assert rec is not None
    assert (rec.epoch, rec.world, rec.members) == (1, 1, (0,))
    assert out["final_world"] == 1
    assert len(out["history"]) == 2          # one death, one relaunch
    gen0, gen1 = out["history"]
    rcs0 = sorted(r.returncode for r in gen0)
    assert rcs0 == [75, 86], f"expected resize+death, got {rcs0}"
    assert all(r.returncode == 0 for r in gen1)
    assert not any(r.timed_out for r in gen0 + gen1)

    # --- the finishing generation trained to completion -------------
    final_model_path = os.path.join(workdir,
                                    f"{out['out_prefix']}_0.txt")
    with open(final_model_path) as f:
        elastic_model = strip_rank_local_params(f.read())

    # --- fixed-world parity run: same epoch bundle, same W'=1 -------
    assert out["snapshot_dir"], "supervisor did not snapshot the bundle"
    parity_dir = str(tmp_path / "parity")
    parity = run_chaos_training(
        parity_dir, rounds=ROUNDS, ckpt_period=CKPT_PERIOD,
        ckpt_dir=out["snapshot_dir"], timeout_s=TIMEOUT_S,
        world=1, elastic=True, resume=True, out_prefix="parity",
        extra_env={"LIGHTGBM_TPU_EPOCH": str(rec.epoch)})
    assert all(r.returncode == 0 for r in parity), \
        "\n".join(r.tail() for r in parity)
    with open(os.path.join(parity_dir, "parity_0.txt")) as f:
        parity_model = strip_rank_local_params(f.read())

    assert elastic_model == parity_model, \
        "elastic-shrunk model differs from fixed-world resume"
