"""Training-engine tests over the objective/metric matrix
(reference tests/python_package_test/test_engine.py)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.callback import (EarlyStopException, early_stopping,
                                   log_evaluation, record_evaluation,
                                   reset_parameter)
from lightgbm_tpu.metrics import AUCMetric

from conftest import make_binary, make_multiclass, make_ranking, \
    make_regression


def _auc(score, y):
    return AUCMetric._auc_fast(score, y > 0, np.ones(len(y)))


class TestRegression:
    def test_l2(self):
        X, y = make_regression()
        dtrain = lgb.Dataset(X[:1600], label=y[:1600])
        dvalid = lgb.Dataset(X[1600:], label=y[1600:], reference=dtrain)
        evals = {}
        bst = lgb.train({"objective": "regression", "metric": "l2",
                         "num_leaves": 15, "verbosity": -1},
                        dtrain, 50, valid_sets=[dvalid],
                        callbacks=[record_evaluation(evals)])
        l2 = evals["valid_0"]["l2"]
        assert l2[-1] < l2[0] * 0.2
        pred = bst.predict(X[1600:])
        mse = float(np.mean((pred - y[1600:]) ** 2))
        assert mse == pytest.approx(l2[-1], rel=1e-4)

    @pytest.mark.parametrize("objective", ["regression_l1", "huber", "fair",
                                           "quantile", "mape"])
    def test_l1_family(self, objective):
        X, y = make_regression()
        y = y - y.min() + 1.0
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": objective, "verbosity": -1,
                         "num_leaves": 15}, dtrain, 30)
        pred = bst.predict(X)
        mae = float(np.mean(np.abs(pred - y)))
        base = float(np.mean(np.abs(np.median(y) - y)))
        assert mae < base * 0.8

    @pytest.mark.parametrize("objective", ["poisson", "gamma", "tweedie"])
    def test_log_link_family(self, objective):
        X, y = make_regression()
        y = np.exp((y - y.mean()) / (2 * y.std())).astype(np.float32)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": objective, "verbosity": -1,
                         "num_leaves": 15}, dtrain, 40)
        pred = bst.predict(X)
        assert np.all(pred > 0)  # log link => positive predictions
        corr = np.corrcoef(pred, y)[0, 1]
        assert corr > 0.8

    def test_quantile_coverage(self):
        X, y = make_regression(n=4000)
        for alpha in (0.1, 0.9):
            dtrain = lgb.Dataset(X, label=y)
            bst = lgb.train({"objective": "quantile", "alpha": alpha,
                             "verbosity": -1, "num_leaves": 31},
                            dtrain, 60)
            cover = float(np.mean(y <= bst.predict(X)))
            assert abs(cover - alpha) < 0.08, (alpha, cover)


class TestBinary:
    def test_auc_improves(self):
        X, y = make_binary()
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary", "verbosity": -1},
                        dtrain, 30)
        assert _auc(bst.predict(X), y) > 0.95

    def test_unbalance_and_scale_pos_weight_conflict(self):
        X, y = make_binary()
        with pytest.raises(Exception):
            lgb.train({"objective": "binary", "is_unbalance": True,
                       "scale_pos_weight": 2.0, "verbosity": -1},
                      lgb.Dataset(X, label=y), 2)

    def test_weights(self):
        X, y = make_binary()
        w = np.where(y > 0, 2.0, 1.0).astype(np.float32)
        dtrain = lgb.Dataset(X, label=y, weight=w)
        bst = lgb.train({"objective": "binary", "verbosity": -1}, dtrain, 20)
        assert _auc(bst.predict(X), y) > 0.9

    def test_sigmoid_param(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "sigmoid": 2.0,
                         "verbosity": -1}, lgb.Dataset(X, label=y), 10)
        assert "sigmoid:2" in bst._host_model().objective


class TestMulticlass:
    def test_softmax(self):
        X, y = make_multiclass()
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "multiclass", "num_class": 4,
                         "metric": "multi_logloss", "verbosity": -1},
                        dtrain, 25)
        pred = bst.predict(X)
        assert pred.shape == (len(y), 4)
        np.testing.assert_allclose(pred.sum(1), 1.0, rtol=1e-5)
        acc = float(np.mean(pred.argmax(1) == y))
        assert acc > 0.85

    def test_ova(self):
        X, y = make_multiclass(n=1500)
        dtrain = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "multiclassova", "num_class": 4,
                         "verbosity": -1}, dtrain, 20)
        pred = bst.predict(X)
        acc = float(np.mean(pred.argmax(1) == y))
        assert acc > 0.8


class TestRanking:
    def test_lambdarank(self):
        X, y, group = make_ranking()
        dtrain = lgb.Dataset(X, label=y, group=group)
        evals = {}
        bst = lgb.train({"objective": "lambdarank", "metric": "ndcg",
                         "eval_at": [5], "verbosity": -1, "num_leaves": 15,
                         "min_data_in_leaf": 5},
                        dtrain, 30, valid_sets=[dtrain],
                        valid_names=["train"],
                        callbacks=[record_evaluation(evals)])
        ndcg = evals["train"]["ndcg@5"]
        assert ndcg[-1] > ndcg[0]
        assert ndcg[-1] > 0.75

    def test_rank_xendcg(self):
        X, y, group = make_ranking()
        dtrain = lgb.Dataset(X, label=y, group=group)
        bst = lgb.train({"objective": "rank_xendcg", "verbosity": -1,
                         "num_leaves": 15, "min_data_in_leaf": 5,
                         "metric": "ndcg", "eval_at": [5]}, dtrain, 30,
                        valid_sets=[dtrain], valid_names=["train"])
        assert bst.best_score["train"]["ndcg@5"] > 0.7


class TestBoostingModes:
    def test_goss(self):
        X, y = make_binary(n=4000)
        bst = lgb.train({"objective": "binary", "boosting": "goss",
                         "top_rate": 0.2, "other_rate": 0.1,
                         "verbosity": -1}, lgb.Dataset(X, label=y), 30)
        assert _auc(bst.predict(X), y) > 0.93

    def test_dart(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "boosting": "dart",
                         "drop_rate": 0.3, "verbosity": -1},
                        lgb.Dataset(X, label=y), 25)
        assert _auc(bst.predict(X), y) > 0.9

    def test_rf(self):
        X, y = make_binary(n=4000)
        bst = lgb.train({"objective": "binary", "boosting": "rf",
                         "bagging_freq": 1, "bagging_fraction": 0.7,
                         "verbosity": -1},
                        lgb.Dataset(X, label=y), 20)
        # averaged forest: prediction in probability space after sigmoid
        assert _auc(bst.predict(X), y) > 0.9

    def test_bagging(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "bagging_freq": 2,
                         "bagging_fraction": 0.6, "bagging_seed": 7,
                         "verbosity": -1}, lgb.Dataset(X, label=y), 25)
        assert _auc(bst.predict(X), y) > 0.93

    def test_feature_fraction(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "feature_fraction": 0.5,
                         "verbosity": -1}, lgb.Dataset(X, label=y), 25)
        assert _auc(bst.predict(X), y) > 0.9


class TestRegularization:
    @pytest.mark.parametrize("param,value", [
        ("lambda_l1", 5.0), ("lambda_l2", 50.0), ("max_delta_step", 0.1),
        ("min_gain_to_split", 1.0), ("path_smooth", 10.0)])
    def test_regularizers_run(self, param, value):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", param: value,
                         "verbosity": -1}, lgb.Dataset(X, label=y), 10)
        assert _auc(bst.predict(X), y) > 0.8

    def test_min_data_in_leaf(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "min_data_in_leaf": 200,
                         "verbosity": -1}, lgb.Dataset(X, label=y), 10)
        model = bst._host_model()
        for t in model.trees:
            assert t.leaf_count.min() >= 200

    def test_max_depth(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "max_depth": 3,
                         "num_leaves": 100, "verbosity": -1},
                        lgb.Dataset(X, label=y), 5)
        # depth-3 tree has at most 8 leaves
        for t in bst._host_model().trees:
            assert t.num_leaves <= 8

    def test_num_leaves(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1}, lgb.Dataset(X, label=y), 5)
        for t in bst._host_model().trees:
            assert 1 < t.num_leaves <= 7


class TestCallbacks:
    def test_early_stopping(self):
        X, y = make_binary(n=3000)
        dtrain = lgb.Dataset(X[:2000], label=y[:2000])
        dvalid = lgb.Dataset(X[2000:], label=y[2000:], reference=dtrain)
        bst = lgb.train({"objective": "binary", "metric": "binary_logloss",
                         "verbosity": -1, "learning_rate": 0.3},
                        dtrain, 500, valid_sets=[dvalid],
                        callbacks=[early_stopping(10, verbose=False)])
        assert bst.best_iteration < 500
        assert bst.current_iteration() >= bst.best_iteration

    def test_record_evaluation(self):
        X, y = make_binary()
        dtrain = lgb.Dataset(X, label=y)
        evals = {}
        lgb.train({"objective": "binary", "metric": "auc", "verbosity": -1},
                  dtrain, 10, valid_sets=[dtrain], valid_names=["train"],
                  callbacks=[record_evaluation(evals)])
        assert len(evals["train"]["auc"]) == 10

    def test_reset_parameter(self):
        X, y = make_binary()
        dtrain = lgb.Dataset(X, label=y)
        lrs = []

        def spy(env):
            lrs.append(env.model.gbdt.shrinkage_rate)
        spy.order = 50
        lgb.train({"objective": "binary", "verbosity": -1}, dtrain, 6,
                  callbacks=[reset_parameter(
                      learning_rate=lambda i: 0.1 * (0.5 ** i)), spy])
        assert lrs[0] == pytest.approx(0.1)
        assert lrs[-1] == pytest.approx(0.1 * 0.5 ** 5)


class TestCustomObjective:
    def test_fobj_feval(self):
        X, y = make_binary()
        dtrain = lgb.Dataset(X, label=y)

        def logloss_obj(score, data):
            p = 1.0 / (1.0 + np.exp(-score))
            lbl = data.get_label()
            return p - lbl, p * (1 - p)

        def my_metric(score, data):
            p = 1.0 / (1.0 + np.exp(-score))
            return ("my_auc", _auc(p, data.get_label()), True)

        evals = {}
        lgb.train({"verbosity": -1}, dtrain, 15, fobj=logloss_obj,
                  feval=my_metric, valid_sets=[dtrain],
                  valid_names=["train"],
                  callbacks=[record_evaluation(evals)])
        assert evals["train"]["my_auc"][-1] > 0.9


class TestCV:
    def test_cv_returns_means(self):
        X, y = make_binary()
        dtrain = lgb.Dataset(X, label=y)
        res = lgb.cv({"objective": "binary", "metric": "auc",
                      "verbosity": -1}, dtrain, 10, nfold=3)
        assert "valid auc-mean" in res
        assert len(res["valid auc-mean"]) == 10
        assert res["valid auc-mean"][-1] > 0.9


class TestModelIO:
    def test_roundtrip_exact(self, tmp_path):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "verbosity": -1},
                        lgb.Dataset(X, label=y), 10)
        path = str(tmp_path / "model.txt")
        bst.save_model(path)
        bst2 = lgb.Booster(model_file=path)
        np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                                   rtol=1e-10)

    def test_dump_json(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "verbosity": -1},
                        lgb.Dataset(X, label=y), 3)
        d = bst.dump_model()
        assert d["num_class"] == 1
        assert len(d["tree_info"]) == 3
        assert "tree_structure" in d["tree_info"][0]

    def test_feature_importance(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "verbosity": -1},
                        lgb.Dataset(X, label=y), 10)
        imp_split = bst.feature_importance("split")
        imp_gain = bst.feature_importance("gain")
        assert imp_split.sum() > 0
        # informative features should dominate
        assert imp_gain[:3].sum() > imp_gain[3:].sum()

    def test_pred_leaf_and_contrib(self):
        X, y = make_binary(n=300)
        bst = lgb.train({"objective": "binary", "verbosity": -1},
                        lgb.Dataset(X, label=y), 5)
        leaves = bst.predict(X[:10], pred_leaf=True)
        assert leaves.shape == (10, 5)
        contrib = bst.predict(X[:10], pred_contrib=True)
        assert contrib.shape == (10, X.shape[1] + 1)
        raw = bst.predict(X[:10], raw_score=True)
        np.testing.assert_allclose(contrib.sum(1), raw, rtol=1e-4)

    def test_refit(self):
        X, y = make_binary()
        bst = lgb.train({"objective": "binary", "verbosity": -1},
                        lgb.Dataset(X, label=y), 5)
        X2, y2 = make_binary(seed=7)
        bst2 = bst.refit(X2, y2)
        assert _auc(bst2.predict(X2), y2) > 0.7


class TestMissingValues:
    def test_nan_handling(self):
        X, y = make_binary()
        Xm = X.copy()
        mask = np.random.RandomState(3).rand(*X.shape) < 0.2
        Xm[mask] = np.nan
        bst = lgb.train({"objective": "binary", "verbosity": -1},
                        lgb.Dataset(Xm, label=y), 20)
        pred = bst.predict(Xm)
        assert np.all(np.isfinite(pred))
        assert _auc(pred, y) > 0.85


class TestCategorical:
    def test_categorical_feature(self):
        r = np.random.RandomState(0)
        n = 3000
        cat = r.randint(0, 8, n).astype(np.float64)
        X = np.column_stack([cat, r.randn(n)])
        effect = np.array([2.0, -1.0, 0.5, 3.0, -2.0, 0.0, 1.0, -0.5])
        y = (effect[cat.astype(int)] + 0.3 * r.randn(n) > 0.5) \
            .astype(np.float32)
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "min_data_in_leaf": 5},
                        lgb.Dataset(X, label=y,
                                    categorical_feature=[0]), 30)
        assert _auc(bst.predict(X), y) > 0.9
        # categorical split must appear in the model text
        assert "num_cat=1" in bst.model_to_string() or \
               any(t.num_cat > 0 for t in bst._host_model().trees)

    def test_categorical_multi_bitset(self):
        """Sorted top-k scan groups several categories per split
        (FindBestThresholdCategoricalInner non-one-hot branch,
        feature_histogram.hpp:375-473)."""
        r = np.random.RandomState(7)
        n = 4000
        cat = r.randint(0, 12, n).astype(np.float64)
        pos = {2, 5, 7, 9}  # these categories drive y=1
        y = np.array([1.0 if int(c) in pos else 0.0 for c in cat],
                     np.float32)
        flip = r.rand(n) < 0.05
        y[flip] = 1.0 - y[flip]
        X = np.column_stack([cat, r.randn(n)])
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "min_data_in_leaf": 5, "num_leaves": 8,
                         "max_cat_to_onehot": 4, "min_data_per_group": 10},
                        lgb.Dataset(X, label=y,
                                    categorical_feature=[0]), 20)
        # perfect category separation under 5% label noise tops out ~0.957
        assert _auc(bst.predict(X), y) > 0.93
        # at least one split must place >1 category on the left
        hm = bst._host_model()
        multi = False
        for t in hm.trees:
            for ci in range(t.num_cat):
                lo, hi = int(t.cat_boundaries[ci]), \
                    int(t.cat_boundaries[ci + 1])
                nset = sum(bin(int(wd)).count("1")
                           for wd in t.cat_threshold[lo:hi])
                if nset > 1:
                    multi = True
        assert multi
        # text round-trip predicts identically
        s = bst.model_to_string()
        bst2 = lgb.Booster(model_str=s)
        np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                                   rtol=1e-5, atol=1e-6)


class TestCVInitModel:
    def test_cv_continues_from_base_model(self):
        from conftest import make_binary
        X, y = make_binary(n=3000, f=8)
        base = lgb.train({"objective": "binary", "verbosity": -1,
                          "num_leaves": 15}, lgb.Dataset(X, label=y), 10)
        res = lgb.cv({"objective": "binary", "verbosity": -1,
                      "num_leaves": 15},
                     lgb.Dataset(X, label=y, free_raw_data=False),
                     num_boost_round=5, nfold=3, init_model=base)
        key = [k for k in res if k.endswith("-mean")][0]
        cold = lgb.cv({"objective": "binary", "verbosity": -1,
                       "num_leaves": 15},
                      lgb.Dataset(X, label=y, free_raw_data=False),
                      num_boost_round=5, nfold=3)
        # continuation starts from the base model's fit: first-round
        # metric must beat the cold start's
        assert res[key][0] < cold[key][0]

    def test_cv_init_model_requires_raw(self):
        from conftest import make_binary
        X, y = make_binary(n=1000, f=5)
        base = lgb.train({"objective": "binary", "verbosity": -1},
                         lgb.Dataset(X, label=y), 3)
        d = lgb.Dataset(X, label=y)
        d.construct()
        d.data = None
        with pytest.raises(ValueError, match="raw data"):
            lgb.cv({"objective": "binary", "verbosity": -1}, d,
                   num_boost_round=2, nfold=2, init_model=base)
