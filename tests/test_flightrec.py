"""Crash flight recorder + cross-rank trace merge + span profiler —
tier-1, subprocess-free.

Every flush trigger is exercised with the real code path and a stubbed
exit: watchdog abort (fake clocks, stubbed abort_fn), injected
rank_death (patched ``os._exit``), a non-finite guard trip, and an
unhandled exception escaping `engine.train`. The true 2-rank kill run
is the chaos harness's job (tests/test_chaos.py, `make postmortem`).
"""

import importlib
import json
import os
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb

faults_mod = importlib.import_module("lightgbm_tpu.reliability.faults")
profile_mod = importlib.import_module(
    "lightgbm_tpu.observability.profile")
from lightgbm_tpu.observability import merge as merge_mod
from lightgbm_tpu.observability.flightrec import (FlightRecorder,
                                                  POSTMORTEM_PREFIX,
                                                  recorder)
from lightgbm_tpu.observability.profile import profiler
from lightgbm_tpu.observability.registry import registry
from lightgbm_tpu.parallel.comm import guarded_allgather
from lightgbm_tpu.reliability import guards
from lightgbm_tpu.reliability.faults import (RANK_DEATH_EXIT_CODE,
                                             faults)
from lightgbm_tpu.reliability.watchdog import (CollectiveGuard,
                                               shutdown_watchdog)

from conftest import make_regression


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    recorder.reset()
    recorder.configure(enabled=True, out_dir="")
    profiler.reset()
    yield
    faults.clear()
    recorder.reset()
    recorder.configure(enabled=True, out_dir="")
    profiler.reset()
    shutdown_watchdog()


def _bundle(dirpath, rank=0):
    path = os.path.join(str(dirpath), f"{POSTMORTEM_PREFIX}{rank}.json")
    assert os.path.exists(path), f"no postmortem bundle at {path}"
    with open(path) as fh:
        return json.load(fh)


# ---------------------------------------------------------------------------
# ring semantics

def test_ring_bounded_and_drop_counted():
    rec = FlightRecorder(capacity=16)
    for i in range(40):
        rec.record("span", f"s{i}")
    snap = rec.snapshot()
    assert snap["events"] == 16
    assert snap["dropped"] == 24
    # the ring keeps the NEWEST events
    assert [e["name"] for e in rec.events()][-1] == "s39"
    assert [e["name"] for e in rec.events()][0] == "s24"


def test_disabled_recorder_is_inert(tmp_path):
    rec = FlightRecorder()
    rec.configure(enabled=False)
    rec.record("span", "x")
    assert rec.snapshot()["events"] == 0
    assert rec.flush("watchdog_abort", out_dir=str(tmp_path)) is None
    assert os.listdir(tmp_path) == []


def test_flush_reason_policy(tmp_path, monkeypatch):
    rec = FlightRecorder()
    rec.record("span", "x")
    # non-fatal reason with no destination: no bundle anywhere
    monkeypatch.chdir(tmp_path)
    assert rec.flush("exception") is None
    assert os.listdir(tmp_path) == []
    # fatal reason with no destination: falls back to the cwd
    path = rec.flush("rank_death")
    assert path is not None and os.path.dirname(path) == str(tmp_path)
    bundle = _bundle(tmp_path)
    assert bundle["reason"] == "rank_death"
    assert bundle["events"][0]["name"] == "x"


def test_flush_is_atomic_and_carries_context(tmp_path):
    rec = FlightRecorder()
    rec.record("collective", "gather", phase="enter", deadline_s=5.0)
    path = rec.flush("watchdog_abort", out_dir=str(tmp_path),
                     extra={"diag": "rank 1 gone"})
    assert path is not None
    assert [n for n in os.listdir(tmp_path)
            if n.startswith(POSTMORTEM_PREFIX)] == ["postmortem_0.json"]
    assert not [n for n in os.listdir(tmp_path) if ".tmp" in n]
    bundle = _bundle(tmp_path)
    assert bundle["diag"] == "rank 1 gone"
    assert bundle["rank"] == 0 and bundle["pid"] == os.getpid()
    # best-effort registry context rides along
    assert "collective" in bundle and "clock_skew" in bundle
    assert rec.snapshot()["flushes"] == 1


# ---------------------------------------------------------------------------
# flush triggers, wired for real

def test_watchdog_abort_flushes_bundle(tmp_path):
    recorder.configure(out_dir=str(tmp_path / "bundles"))
    fired = threading.Event()
    g = CollectiveGuard(0.08, rank=0, world=2,
                        heartbeat_dir=str(tmp_path / "hb"),
                        heartbeat_interval_s=0.02,
                        first_deadline_factor=1.0,
                        abort_fn=lambda diag: fired.set())
    g.start()
    try:
        g.enter("gather")
        assert fired.wait(timeout=10.0), "watchdog monitor never fired"
    finally:
        g.exit_()
        g.stop()
    bundle = _bundle(tmp_path / "bundles")
    assert bundle["reason"] == "watchdog_abort"
    kinds = [(e["kind"], e["name"]) for e in bundle["events"]]
    assert ("collective", "gather") in kinds     # the hung bracket
    assert kinds[-1] == ("abort", "watchdog")    # the last word
    abort_ev = bundle["events"][-1]
    assert "gather" in abort_ev["diag"]


def test_watchdog_abort_stub_without_dir_leaves_no_bundle(
        tmp_path, monkeypatch):
    # existing tier-1 watchdog tests stub the abort with no bundle dir
    # configured — they must not litter the cwd with postmortems
    monkeypatch.chdir(tmp_path)
    fired = threading.Event()
    g = CollectiveGuard(0.05, rank=0, world=2,
                        first_deadline_factor=1.0,
                        abort_fn=lambda diag: fired.set())
    g.start()
    try:
        g.enter("gather")
        assert fired.wait(timeout=10.0)
    finally:
        g.exit_()
        g.stop()
    assert not [n for n in os.listdir(tmp_path)
                if n.startswith(POSTMORTEM_PREFIX)]


def test_rank_death_flushes_bundle(tmp_path, monkeypatch):
    recorder.configure(out_dir=str(tmp_path))
    exits = []
    monkeypatch.setattr(faults_mod.os, "_exit", exits.append)
    faults.schedule("collective_psum", fail=1, mode="rank_death")
    faults.inject("collective_psum")
    assert exits == [RANK_DEATH_EXIT_CODE]
    bundle = _bundle(tmp_path)
    assert bundle["reason"] == "rank_death"
    # the fault hit itself is the last recorded event: the bundle names
    # the site the rank died in
    assert bundle["events"][-1]["kind"] == "fault"
    assert bundle["events"][-1]["name"] == "collective_psum"
    assert bundle["events"][-1]["mode"] == "rank_death"


def test_guard_trip_flushes_bundle(tmp_path):
    recorder.configure(out_dir=str(tmp_path))
    guards.trip("gradients", "warn", iteration=7)
    bundle = _bundle(tmp_path)
    assert bundle["reason"] == "guard_nonfinite"
    assert bundle["events"][-1] == {
        **bundle["events"][-1], "kind": "guard", "name": "gradients",
        "policy": "warn", "iteration": 7}


def test_engine_unhandled_exception_flushes_bundle(tmp_path):
    X, y = make_regression(n=200, f=4)
    dtrain = lgb.Dataset(X, label=y)

    def _boom(env):
        raise RuntimeError("callback exploded")

    with pytest.raises(RuntimeError, match="callback exploded"):
        lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": -1, "flightrec_dir": str(tmp_path)},
                  dtrain, 5, callbacks=[_boom])
    bundle = _bundle(tmp_path)
    assert bundle["reason"] == "exception"
    last = bundle["events"][-1]
    assert last["kind"] == "exception" and last["name"] == "engine.train"
    assert last["exc_type"] == "RuntimeError"
    assert "callback exploded" in last["exc"]


def test_cli_failure_before_booster_flushes_bundle(tmp_path):
    # the CLI arms the recorder from the parsed config BEFORE any
    # Booster exists: a bad data path must still honor flightrec_dir=
    import subprocess
    import sys
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.cli", "task=train",
         "data=DOES_NOT_EXIST.csv", "objective=binary",
         f"flightrec_dir={tmp_path}"],
        capture_output=True, text=True, cwd=str(tmp_path),
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__)))},
        timeout=120)
    assert proc.returncode != 0
    assert "DOES_NOT_EXIST.csv" in proc.stderr, proc.stderr
    bundle = _bundle(tmp_path)
    assert bundle["reason"] == "exception"
    assert bundle["events"][-1]["exc_type"] == "FileNotFoundError"


def test_collective_brackets_and_clock_ride_the_ring():
    before = registry.clock_skew_snapshot()["samples"]
    out = guarded_allgather(np.arange(4, dtype=np.float64), "gather")
    np.testing.assert_array_equal(out, np.arange(4, dtype=np.float64))
    assert registry.clock_skew_snapshot()["samples"] == before + 1
    # single process: no guard bracket (collective_guard no-ops — the
    # bracket records are pinned by the watchdog tests above), but the
    # clock sample piggybacked on the allgather still rides the ring
    kinds = [(e["kind"], e["name"]) for e in recorder.events()]
    assert ("clock", "gather") in kinds
    # single process: one wall stamp, zero skew
    sample = registry.clock_samples()[-1]
    assert sample["site"] == "gather" and len(sample["walls"]) == 1


def test_flightrec_family_in_snapshot_and_prometheus():
    recorder.record("span", "x")
    snap = registry.snapshot()
    assert snap["flightrec"]["events"] >= 1
    assert set(snap["clock_skew"]) == {"samples", "last_skew_s",
                                       "max_skew_s"}
    text = registry.prometheus_text()
    assert "lightgbm_tpu_flightrec_events" in text
    assert "lightgbm_tpu_clock_skew_samples" in text


# ---------------------------------------------------------------------------
# cross-rank merge: synthetic 2-rank traces with a known 5s clock skew

def _rank_trace(rank, epoch_wall, events, clock_samples):
    return {"traceEvents": events, "displayTimeUnit": "ms",
            merge_mod.META_KEY: {"rank": rank, "epoch_wall": epoch_wall,
                                 "clock_samples": clock_samples}}


def test_merge_round_trip_recovers_injected_offset(tmp_path):
    # rank 1's wall clock runs exactly 5.0s ahead of rank 0's; three
    # collective samples carry arrival skews of +0.2s, 0.0s and -0.1s
    samples = [
        {"site": "collective_psum", "walls": [1010.0, 1015.2]},
        {"site": "collective_psum", "walls": [1020.0, 1025.0]},
        {"site": "collective_psum", "walls": [1030.1, 1035.0]},
    ]
    ev0 = [{"name": "train", "ph": "X", "ts": 0.0, "dur": 2e6,
            "pid": 0, "tid": 0}]
    ev1 = [{"name": "train", "ph": "X", "ts": 0.0, "dur": 2e6,
            "pid": 0, "tid": 0}]
    for rank, epoch, ev in ((0, 1000.0, ev0), (1, 1005.0, ev1)):
        with open(tmp_path / f"trace_r{rank}.json", "w") as fh:
            json.dump(_rank_trace(rank, epoch, ev, samples), fh)
    # a non-trace JSON in the same dir must be ignored, not crash
    (tmp_path / "postmortem_0.json").write_text('{"reason": "x"}')

    out, merged = merge_mod.merge_directory(str(tmp_path))
    assert os.path.basename(out) == merge_mod.MERGED_DEFAULT

    info = merged["lightgbm_tpu_merge"]
    assert info["ranks"] == [0, 1] and info["base_rank"] == 0
    # median of (5.2, 5.0, 4.9) recovers the injected 5.0s offset
    assert info["clock_offsets_s"]["1"] == pytest.approx(5.0, abs=1e-6)
    skews = sorted(c["skew_ms"] for c in info["collectives"])
    assert skews == pytest.approx([0.0, 100.0, 200.0], abs=1e-3)

    # both ranks' epochs correct to the same origin: rank 1's "train"
    # slice starts at ts=0 like rank 0's, not 5s later
    starts = {ev["pid"]: ev["ts"] for ev in merged["traceEvents"]
              if ev.get("name") == "train"}
    assert starts[0] == pytest.approx(0.0, abs=1e3)   # us tolerance 1ms
    assert starts[1] == pytest.approx(0.0, abs=1e3)
    skew_events = [ev for ev in merged["traceEvents"]
                   if ev.get("cat") == "lightgbm_tpu_clock"]
    assert len(skew_events) == 3
    assert all(ev["name"] == "skew:collective_psum"
               for ev in skew_events)


def test_merge_cli(tmp_path, capsys):
    from lightgbm_tpu.observability.__main__ import main
    samples = [{"site": "g", "walls": [10.0, 10.5]}]
    for rank in (0, 1):
        with open(tmp_path / f"trace_r{rank}.json", "w") as fh:
            json.dump(_rank_trace(rank, 5.0, [], samples), fh)
    assert main(["merge", str(tmp_path)]) == 0
    outp = capsys.readouterr().out
    assert f"wrote {tmp_path}" in outp.replace(os.sep + 'merged', '/merged') \
        or "wrote" in outp
    assert os.path.exists(tmp_path / merge_mod.MERGED_DEFAULT)
    # empty dir: a clean error, not a traceback
    empty = tmp_path / "empty"
    empty.mkdir()
    assert main(["merge", str(empty)]) == 1
    assert main(["bogus"]) == 2


def test_trace_dump_is_rank_tagged(tmp_path):
    registry.reset()
    registry.enable()
    try:
        with registry.trace.span("unit_work"):
            pass
        path = str(tmp_path / "trace_r0.json")
        registry.dump_trace(path, fmt="chrome")
    finally:
        registry.disable()
        registry.reset()
    doc = merge_mod.load_rank_trace(path)
    assert doc is not None, "dump_trace output not rank-taggged"
    meta = doc[merge_mod.META_KEY]
    assert meta["rank"] == 0 and meta["epoch_wall"] > 0
    merged = merge_mod.merge_traces([path])
    assert any(ev.get("name") == "unit_work"
               for ev in merged["traceEvents"])


# ---------------------------------------------------------------------------
# span profiler: budget + degrade-to-noop

def test_profiler_budget_and_match(tmp_path, monkeypatch):
    started, stopped = [], []
    monkeypatch.setattr(profile_mod, "_start_trace", started.append)
    monkeypatch.setattr(profile_mod, "_stop_trace",
                        lambda: stopped.append(True))
    profiler.configure(spans="sharded_*", out_dir=str(tmp_path),
                       max_captures=2)
    with profiler.capture("unrelated") as live:
        assert live is False
    for _ in range(3):
        with profiler.capture("sharded_grow") as live:
            pass
    snap = profiler.snapshot()
    assert snap["captures"] == 2 and snap["armed"] == 0
    assert len(started) == 2 and len(stopped) == 2
    assert started[0].startswith(str(tmp_path))


def test_profiler_degrades_on_failure(monkeypatch, tmp_path):
    def _boom(log_dir):
        raise RuntimeError("no profiler backend")
    monkeypatch.setattr(profile_mod, "_start_trace", _boom)
    profiler.configure(spans="pipeline_block", out_dir=str(tmp_path),
                       max_captures=4)
    with profiler.capture("pipeline_block") as live:
        assert live is False           # degraded, not raised
    snap = profiler.snapshot()
    assert snap["failed"] == 1 and snap["armed"] == 0
    # once failed, re-configure keeps it disarmed for the process
    profiler.configure(spans="pipeline_block", out_dir=str(tmp_path))
    assert profiler.snapshot()["armed"] == 0
