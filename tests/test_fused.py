"""Fused multi-tree training (boosting/fused.py, Booster.update_batch).

update_batch(k) must be semantically identical to k update() calls:
- ineligible configs (CPU scatter path here) fall back to a plain loop;
- the fused scan itself must give bit-identical results for one scan of
  k trees vs k scans of 1 tree (scan mechanics, stacking, iteration
  indexing, score carry).
"""

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb


def _data(n=600, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 7, "learning_rate": 0.2,
          "max_bin": 31, "verbosity": -1, "min_data_in_leaf": 5}


def _booster(X, y):
    ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
    return lgb.Booster(params=dict(PARAMS), train_set=ds)


class TestFallbackLoop:
    def test_update_batch_equals_update_loop(self):
        X, y = _data()
        a = _booster(X, y)
        b = _booster(X, y)
        for _ in range(5):
            a.update()
        b.update_batch(5)
        assert a.current_iteration() == b.current_iteration() == 5
        np.testing.assert_array_equal(
            np.asarray(a.gbdt.train_score), np.asarray(b.gbdt.train_score))
        assert a.model_to_string() == b.model_to_string()


@pytest.mark.slow
class TestFusedScan:
    def _mxu_booster(self, X, y):
        bst = _booster(X, y)
        bst.update()  # iteration 0 runs the normal (scatter) path
        g = bst.gbdt
        g._hist_impl = "mxu"  # force the fused-eligible path on CPU
        g._mxu_interpret = True  # Pallas interpret mode (no TPU here)
        g._fused_run = None
        return bst

    def test_fused_equals_per_iteration_mxu(self):
        # the core contract: the fused scan must grow the SAME trees as
        # k train_one_iter calls through the per-iteration MXU path
        X, y = _data(seed=4)
        a = self._mxu_booster(X, y)
        b = self._mxu_booster(X, y)
        a.update_batch(3)
        for _ in range(3):
            b.update()
        assert a.current_iteration() == b.current_iteration() == 4
        np.testing.assert_array_equal(
            np.asarray(a.gbdt.train_score), np.asarray(b.gbdt.train_score))
        assert a.model_to_string() == b.model_to_string()

    @pytest.mark.parametrize("extra_params", [
        {"bagging_fraction": 0.7, "bagging_freq": 2},          # bagging
        {"boosting": "goss", "top_rate": 0.3, "other_rate": 0.3},  # GOSS
    ])
    def test_fused_sampling_equals_per_iteration(self, extra_params):
        # round-4 eligibility ring: bagging recomputed statelessly
        # in-scan; GOSS rides pre-drawn keys (gbdt._fused_sample_fn)
        X, y = _data(seed=6)
        boosters = []
        for _ in range(2):
            ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
            bst = lgb.Booster(params={**PARAMS, **extra_params},
                              train_set=ds)
            bst.update()
            g = bst.gbdt
            g._hist_impl = "mxu"
            g._mxu_interpret = True
            g._fused_run = None
            boosters.append(bst)
        a, b = boosters
        assert a.gbdt._fused_eligible()
        a.update_batch(3)
        for _ in range(3):
            b.update()
        assert a.current_iteration() == b.current_iteration() == 4
        np.testing.assert_array_equal(
            np.asarray(a.gbdt.train_score), np.asarray(b.gbdt.train_score))
        assert a.model_to_string() == b.model_to_string()

    def test_fused_multiclass_equals_per_iteration(self):
        rng = np.random.RandomState(8)
        X = rng.randn(600, 5).astype(np.float32)
        y = (X[:, 0] + 0.3 * rng.randn(600) > 0).astype(np.float32) + \
            (X[:, 1] > 0.5).astype(np.float32)
        boosters = []
        for _ in range(2):
            ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
            bst = lgb.Booster(
                params={**PARAMS, "objective": "multiclass",
                        "num_class": 3}, train_set=ds)
            bst.update()
            g = bst.gbdt
            g._hist_impl = "mxu"
            g._mxu_interpret = True
            g._fused_run = None
            boosters.append(bst)
        a, b = boosters
        assert a.gbdt._fused_eligible()
        a.update_batch(3)
        for _ in range(3):
            b.update()
        assert a.current_iteration() == b.current_iteration() == 4
        assert len(a.gbdt.trees) == len(b.gbdt.trees) == 12
        assert a.gbdt.tree_class == b.gbdt.tree_class
        np.testing.assert_array_equal(
            np.asarray(a.gbdt.train_score), np.asarray(b.gbdt.train_score))
        assert a.model_to_string() == b.model_to_string()

    def test_scan_of_k_equals_k_scans(self):
        X, y = _data(seed=3)
        a = self._mxu_booster(X, y)
        b = self._mxu_booster(X, y)
        a.update_batch(3)
        for _ in range(3):
            b.update_batch(1)
        assert a.current_iteration() == b.current_iteration() == 4
        np.testing.assert_array_equal(
            np.asarray(a.gbdt.train_score), np.asarray(b.gbdt.train_score))
        for ta, tb in zip(a.gbdt.trees[1:], b.gbdt.trees[1:]):
            for fld in ("split_feature", "threshold_bin", "left", "right"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(ta, fld)),
                    np.asarray(getattr(tb, fld)), err_msg=fld)
            np.testing.assert_array_equal(np.asarray(ta.leaf_value),
                                          np.asarray(tb.leaf_value))
        assert a.model_to_string() == b.model_to_string()


@pytest.mark.slow
class TestFusedValidSets:
    """Round-5 eligibility widening: valid sets ride the fused scan —
    the stacked block is replayed over each valid set after the
    dispatch (fused.stacked_score_traj), so valid scores and the
    per-iteration trajectory match k train_one_iter calls exactly, and
    engine.train's block dispatch early-stops identically to the
    per-iteration loop (reference eval cadence, gbdt.cpp:469-572)."""

    def _mxu_booster(self, X, y, Xv, yv, extra=None):
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        bst = lgb.Booster(params={**PARAMS, **(extra or {})},
                          train_set=ds)
        bst.add_valid(lgb.Dataset(Xv, label=yv), "v")
        bst.update()
        g = bst.gbdt
        g._hist_impl = "mxu"
        g._mxu_interpret = True
        g._fused_run = None
        return bst

    def test_valid_scores_and_trajectory_match_per_iteration(self):
        X, y = _data(seed=11)
        Xv, yv = _data(n=200, seed=12)
        a = self._mxu_booster(X, y, Xv, yv)
        b = self._mxu_booster(X, y, Xv, yv)
        assert a.gbdt._fused_eligible()
        a.update_batch(3)
        traj = a.gbdt._fused_valid_traj
        assert traj is not None and len(traj) == 1
        assert traj[0].shape[0] == 3
        per_iter = []
        for _ in range(3):
            b.update()
            per_iter.append(np.asarray(b.gbdt.valid_scores[0]).copy())
        assert a.current_iteration() == b.current_iteration() == 4
        assert a.model_to_string() == b.model_to_string()
        # final valid scores agree, and every trajectory point equals
        # the per-iteration valid score at that iteration
        np.testing.assert_array_equal(
            np.asarray(a.gbdt.valid_scores[0]), per_iter[-1])
        for j in range(3):
            np.testing.assert_array_equal(
                np.asarray(traj[0][j]), per_iter[j], err_msg=f"iter {j}")

    def test_engine_block_early_stopping_matches_per_iteration(
            self, monkeypatch):
        from lightgbm_tpu import engine as engine_mod

        class _MxuBooster(lgb.Booster):
            def __init__(self, *args, **kw):
                super().__init__(*args, **kw)
                self.gbdt._hist_impl = "mxu"
                self.gbdt._mxu_interpret = True

        monkeypatch.setattr(engine_mod, "Booster", _MxuBooster)
        X, y = _data(seed=13)
        rng = np.random.RandomState(14)
        Xv = rng.randn(200, 5).astype(np.float32)
        yv = (Xv[:, 0] + 1.5 * rng.randn(200) > 0).astype(np.float32)
        results = []
        for block in (1, 5):
            bst = engine_mod.train(
                {**PARAMS, "early_stopping_round": 2,
                 "fused_block_size": block},
                lgb.Dataset(X, label=y, params={"max_bin": 31}),
                num_boost_round=25,
                valid_sets=[lgb.Dataset(Xv, label=yv)])
            results.append(bst)
        a, b = results
        assert a.best_iteration == b.best_iteration
        assert a.current_iteration() == b.current_iteration()
        assert dict(a.best_score) == dict(b.best_score)
        # identical models modulo the serialized fused_block_size param
        # itself (dispatch granularity is config, not model content)
        strip = lambda s: [ln for ln in s.splitlines()
                           if not ln.startswith("[fused_block_size")]
        assert strip(a.model_to_string()) == strip(b.model_to_string())
        # the stop must have engaged before the full round budget,
        # otherwise this test proves nothing about rollback
        assert a.current_iteration() < 25


@pytest.mark.slow
class TestEngineBlockGating:
    def test_custom_callback_forces_per_iteration_cadence(
            self, monkeypatch):
        # a user callback that reads model state is NOT block_safe: the
        # engine must fall back to per-iteration dispatch so the
        # callback never observes future trees (round-5 review finding)
        from lightgbm_tpu import engine as engine_mod

        class _MxuBooster(lgb.Booster):
            def __init__(self, *args, **kw):
                super().__init__(*args, **kw)
                self.gbdt._hist_impl = "mxu"
                self.gbdt._mxu_interpret = True

        monkeypatch.setattr(engine_mod, "Booster", _MxuBooster)
        X, y = _data(seed=21)
        Xv, yv = _data(n=150, seed=22)
        seen = []

        def snoop(env):
            seen.append(env.model.current_iteration())

        bst = engine_mod.train(
            {**PARAMS, "fused_block_size": 4},
            lgb.Dataset(X, label=y, params={"max_bin": 31}),
            num_boost_round=6,
            valid_sets=[lgb.Dataset(Xv, label=yv)],
            callbacks=[snoop])
        # per-iteration cadence: the callback saw every iteration count
        # as it happened, never a block-end state at an inner iteration
        assert seen == [1, 2, 3, 4, 5, 6]
        assert bst.current_iteration() == 6


@pytest.mark.slow
class TestFusedValidMulticlass:
    def test_multiclass_valid_trajectory_matches_per_iteration(self):
        # the stacked_score_traj num_class>1 branch: per-class column
        # updates must reproduce k per-iteration valid updates exactly
        rng = np.random.RandomState(31)
        X = rng.randn(500, 5).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32) + (X[:, 1] > 0.5)
        Xv = rng.randn(150, 5).astype(np.float32)
        yv = (Xv[:, 0] > 0).astype(np.float32) + (Xv[:, 1] > 0.5)
        boosters = []
        for _ in range(2):
            ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
            bst = lgb.Booster(params={**PARAMS, "objective": "multiclass",
                                      "num_class": 3}, train_set=ds)
            bst.add_valid(lgb.Dataset(Xv, label=yv), "v")
            bst.update()
            g = bst.gbdt
            g._hist_impl = "mxu"
            g._mxu_interpret = True
            g._fused_run = None
            boosters.append(bst)
        a, b = boosters
        assert a.gbdt._fused_eligible()
        a.update_batch(2)
        # pin that the FUSED dispatch actually ran — a silent
        # per-iteration fallback would make this test pass vacuously
        assert getattr(a.gbdt, "_fused_failures", 0) == 0
        assert not getattr(a.gbdt, "_fused_disabled", False)
        traj = a.gbdt._fused_valid_traj
        assert traj is not None and traj[0].shape[0] == 2
        per_iter = []
        for _ in range(2):
            b.update()
            per_iter.append(np.asarray(b.gbdt.valid_scores[0]).copy())
        np.testing.assert_array_equal(
            np.asarray(a.gbdt.valid_scores[0]), per_iter[-1])
        for j in range(2):
            np.testing.assert_array_equal(
                np.asarray(traj[0][j]), per_iter[j], err_msg=f"iter {j}")
