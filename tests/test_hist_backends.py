"""Histogram-backend parity suite (`make kernels`).

The hist_backend contract (config.py, docs/Performance.md): in the
quantized posture the mxu one-hot kernel, the Pallas scatter kernel
(histogram_pallas.py), and the XLA segment-sum oracle produce
BIT-IDENTICAL histograms — integer gradient channels are bf16-exact and
f32 accumulation of integer sums is exact below 2^24 — so trees and
model.txt are byte-equal across backends and `hist_backend=auto` is
purely a speed knob. Exact (non-quantized) mode rides hi/lo bf16
channel pairs and is only ~f32-accurate; its error bound is pinned
here too.

The fast subset (not slow) is tier-1; the slow subset adds tree- and
model-level byte parity through the boosters.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.kernels

import jax
import jax.numpy as jnp

from lightgbm_tpu.data import BinnedDataset, Metadata
from lightgbm_tpu.learner.histogram import build_histograms
from lightgbm_tpu.learner.histogram_mxu import (build_histograms_mxu_auto,
                                                pack_bins_4bit,
                                                pack_route_tables,
                                                quantize_gradients,
                                                route_rows_mxu,
                                                unpack_bins_4bit)
from lightgbm_tpu.learner.histogram_pallas import (build_histograms_scatter,
                                                   partition_rows)

S = 8  # frontier slots for the kernel-level tests


def _inputs(n=2000, f=6, seed=0, max_bin=63, bin_dist="uniform"):
    """(bins, grad, hess, cnt, slot, bmax) with a chosen bin
    distribution; slots include parked rows (-1)."""
    rng = np.random.RandomState(seed)
    if bin_dist == "uniform":
        bins = rng.randint(0, max_bin, size=(n, f))
    elif bin_dist == "one_bin":            # every row in one bin
        bins = np.full((n, f), 3)
    elif bin_dist == "nan_heavy":          # 60% of rows in the NaN bin
        bins = rng.randint(0, max_bin - 1, size=(n, f))
        nan_rows = rng.rand(n) < 0.6
        bins[nan_rows] = max_bin - 1       # NaN bin = last bin
    elif bin_dist == "boundary15":         # 4-bit packing boundary
        assert max_bin == 16
        bins = rng.randint(0, 16, size=(n, f))
        bins[: n // 4] = 15                # pile on the top nibble value
    else:
        raise ValueError(bin_dist)
    bins = bins.astype(np.uint8)
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    hess = jnp.asarray(rng.rand(n).astype(np.float32) + 0.1)
    cnt = jnp.ones(n, jnp.float32)
    slot = jnp.asarray(rng.randint(-1, S, size=n).astype(np.int32))
    return jnp.asarray(bins), grad, hess, cnt, slot, max_bin


def _quant(grad, hess, seed=0):
    gq, hq, _, _ = quantize_gradients(grad, hess, jax.random.PRNGKey(seed))
    return gq, hq


class TestScatterKernelParity:
    """Pallas scatter vs MXU one-hot vs the XLA oracle."""

    def test_exact_mode_matches_oracle(self):
        bins, g, h, cnt, slot, bmax = _inputs()
        hs = build_histograms_scatter(bins, g, h, cnt, slot, num_slots=S,
                                      bmax=bmax, interpret=True)
        hr = build_histograms(bins, g, h, slot, cnt, num_slots=S,
                              bmax=bmax)
        np.testing.assert_allclose(np.asarray(hs), np.asarray(hr)[:S],
                                   rtol=1e-4, atol=1e-3)

    def test_exact_mode_f32_error_bound(self):
        # pin the accumulation-precision contract: hi/lo bf16 channel
        # pairs with f32 accumulation land within 1e-4 relative of a
        # float64 host reduce. A regression to single-bf16 sums (~2^-9
        # relative) fails this by two orders of magnitude.
        bins, g, h, cnt, slot, bmax = _inputs(n=4000, seed=5)
        hs = np.asarray(build_histograms_scatter(
            bins, g, h, cnt, slot, num_slots=S, bmax=bmax,
            interpret=True))
        bn, sl = np.asarray(bins), np.asarray(slot)
        g64 = np.asarray(g, np.float64)
        h64 = np.asarray(h, np.float64)
        want = np.zeros((S, bn.shape[1], bmax, 3))
        for r in range(bn.shape[0]):
            if sl[r] < 0:
                continue
            for f in range(bn.shape[1]):
                want[sl[r], f, bn[r, f]] += (g64[r], h64[r], 1.0)
        scale = np.abs(want).max()
        assert np.abs(hs - want).max() <= 1e-4 * scale + 1e-5

    @pytest.mark.parametrize("bin_dist", ["uniform", "one_bin",
                                          "nan_heavy"])
    def test_quantized_bit_identical(self, bin_dist):
        # the byte-parity foundation: all three backends, same bits
        bins, g, h, cnt, slot, bmax = _inputs(bin_dist=bin_dist, seed=2)
        gq, hq = _quant(g, h)
        hs = build_histograms_scatter(bins, gq, hq, cnt, slot,
                                      num_slots=S, bmax=bmax,
                                      quantized=True, interpret=True)
        hm = build_histograms_mxu_auto(bins, gq, hq, cnt, slot,
                                       num_slots=S, bmax=bmax,
                                       quantized=True, interpret=True)
        hr = build_histograms(bins, gq, hq, slot, cnt, num_slots=S,
                              bmax=bmax)
        np.testing.assert_array_equal(np.asarray(hs), np.asarray(hm))
        np.testing.assert_array_equal(np.asarray(hs),
                                      np.asarray(hr)[:S])

    def test_quantized_const_hess_channels(self):
        # const-hessian drops the hessian dot channel; the kernels
        # reconstruct it as const x count, exactly
        bins, g, h, cnt, slot, bmax = _inputs(seed=3)
        gq, _ = _quant(g, None)
        ch = 1.0
        hs = build_histograms_scatter(bins, gq, h, cnt, slot,
                                      num_slots=S, bmax=bmax,
                                      quantized=True, const_hess=ch,
                                      interpret=True)
        hm = build_histograms_mxu_auto(bins, gq, h, cnt, slot,
                                       num_slots=S, bmax=bmax,
                                       quantized=True, const_hess=ch,
                                       interpret=True)
        np.testing.assert_array_equal(np.asarray(hs), np.asarray(hm))
        np.testing.assert_array_equal(np.asarray(hs)[..., 1],
                                      np.asarray(hs)[..., 2] * ch)

    def test_packed4_boundary_bin15(self):
        # 4-bit packed storage at the nibble boundary: bin id 15 must
        # land in bin 15, not bleed into a neighbor feature's low nibble
        bins, g, h, cnt, slot, bmax = _inputs(max_bin=16,
                                              bin_dist="boundary15",
                                              seed=4)
        f = bins.shape[1]
        packed = jnp.asarray(pack_bins_4bit(np.asarray(bins)))
        gq, hq = _quant(g, h)
        hs = build_histograms_scatter(packed, gq, hq, cnt, slot,
                                      num_slots=S, bmax=bmax,
                                      num_features=f, quantized=True,
                                      interpret=True)
        hr = build_histograms(bins, gq, hq, slot, cnt, num_slots=S,
                              bmax=bmax)
        np.testing.assert_array_equal(np.asarray(hs),
                                      np.asarray(hr)[:S])

    def test_single_row_and_empty_slots(self):
        # one row per live slot, some slots empty: no cross-slot bleed,
        # empty slots all-zero
        n, f, bmax = 5, 4, 31
        rng = np.random.RandomState(9)
        bins = jnp.asarray(rng.randint(0, bmax, size=(n, f))
                           .astype(np.uint8))
        g = jnp.asarray(rng.randn(n).astype(np.float32))
        h = jnp.ones(n, jnp.float32)
        cnt = jnp.ones(n, jnp.float32)
        slot = jnp.asarray(np.array([0, 2, 4, 5, 7], np.int32))
        gq, hq = _quant(g, h)
        hs = np.asarray(build_histograms_scatter(
            bins, gq, hq, cnt, slot, num_slots=S, bmax=bmax,
            quantized=True, interpret=True))
        hr = np.asarray(build_histograms(bins, gq, hq, slot, cnt,
                                         num_slots=S, bmax=bmax))[:S]
        np.testing.assert_array_equal(hs, hr)
        for s in (1, 3, 6):
            assert not hs[s].any()

    def test_precomputed_slot_counts_match(self):
        # feeding route-emitted counts must be a pure shortcut
        bins, g, h, cnt, slot, bmax = _inputs(seed=6)
        gq, hq = _quant(g, h)
        sl = np.asarray(slot)
        counts = jnp.asarray(np.bincount(sl[sl >= 0], minlength=S)
                             .astype(np.int32))
        a = build_histograms_scatter(bins, gq, hq, cnt, slot,
                                     num_slots=S, bmax=bmax,
                                     quantized=True, interpret=True)
        b = build_histograms_scatter(bins, gq, hq, cnt, slot,
                                     num_slots=S, bmax=bmax,
                                     quantized=True, slot_counts=counts,
                                     interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestPartitionRows:
    def test_padded_layout_invariants(self):
        rng = np.random.RandomState(1)
        n, nb = 997, 128
        slot = jnp.asarray(rng.randint(-1, S, size=n).astype(np.int32))
        block_slot, src = partition_rows(slot, num_slots=S, row_block=nb)
        bs, sr = np.asarray(block_slot), np.asarray(src)
        sl = np.asarray(slot)
        assert sr.shape[0] == bs.shape[0] * nb
        # every real row appears exactly once
        real = sr[sr < n]
        assert sorted(real.tolist()) == list(range(n))
        # every REAL row sits in a block of its own slot (parked rows in
        # the trash slot S); padding positions carry the dummy row n and
        # may sit anywhere — they contribute zeros
        pos_slot = bs[np.arange(sr.shape[0]) // nb]
        live = sr < n
        expect = np.where(sl[sr[live]] < 0, S, sl[sr[live]])
        np.testing.assert_array_equal(pos_slot[live], expect)


class TestRouteEmitCounts:
    """route_rows_mxu(emit_counts=True): the fused routing+partition
    sweep returns the same routing plus exact per-slot counts."""

    def _route_args(self, n=1500, f=4, bmax=31, seed=0):
        rng = np.random.RandomState(seed)
        bins = jnp.asarray(rng.randint(0, bmax, size=(n, f))
                           .astype(np.uint8))
        m = 8
        z = np.zeros(m, np.int32)
        split_mask = jnp.asarray(np.array([1] + [0] * (m - 1), bool))
        feat = jnp.asarray(z)                       # split on feature 0
        thr = jnp.asarray(z + bmax // 2)
        default_left = jnp.asarray(np.zeros(m, bool))
        is_cat = jnp.asarray(np.zeros(m, bool))
        child_l = jnp.asarray(z + 1)
        child_r = jnp.asarray(z + 2)
        slot_of_node = jnp.asarray(
            np.array([-1, 0, 1] + [-1] * (m - 3), np.int32))
        cat_bitset = jnp.zeros((m, 1), jnp.uint32)
        tbl, member = pack_route_tables(
            split_mask, feat, thr, default_left, is_cat, child_l,
            child_r, slot_of_node, cat_bitset, m, bmax)
        feat_tbl = jnp.stack(
            [jnp.full(f, bmax, jnp.float32), jnp.zeros(f, jnp.float32)],
            axis=1)
        row_node = jnp.zeros(n, jnp.int32)
        return bins, row_node, tbl, member, feat_tbl, bmax

    def test_counts_match_bincount(self):
        bins, row_node, tbl, member, feat_tbl, bmax = self._route_args()
        rn, rs, counts = route_rows_mxu(bins, row_node, tbl, member,
                                        feat_tbl, emit_counts=True,
                                        num_slots=4, interpret=True)
        sl = np.asarray(rs)
        want = np.bincount(sl[sl >= 0], minlength=4)
        np.testing.assert_array_equal(np.asarray(counts), want)
        assert set(np.unique(sl)) <= {0, 1}

    def test_route_outputs_unchanged(self):
        bins, row_node, tbl, member, feat_tbl, bmax = self._route_args(
            seed=2)
        rn0, rs0 = route_rows_mxu(bins, row_node, tbl, member, feat_tbl,
                                  interpret=True)
        rn1, rs1, _ = route_rows_mxu(bins, row_node, tbl, member,
                                     feat_tbl, emit_counts=True,
                                     num_slots=4, interpret=True)
        np.testing.assert_array_equal(np.asarray(rn0), np.asarray(rn1))
        np.testing.assert_array_equal(np.asarray(rs0), np.asarray(rs1))


class TestPack4BitValidation:
    def test_refuses_wide_bins(self):
        bins = np.zeros((32, 4), np.uint8)
        bins[7, 2] = 16                      # exceeds the 4-bit limit
        assert pack_bins_4bit(bins) is None  # refuse, don't truncate

    def test_valid_packing_roundtrips(self):
        rng = np.random.RandomState(0)
        bins = rng.randint(0, 16, size=(64, 5)).astype(np.uint8)
        packed = pack_bins_4bit(bins)
        assert packed is not None
        np.testing.assert_array_equal(
            np.asarray(unpack_bins_4bit(jnp.asarray(packed), 5)), bins)


class TestBackendResolution:
    """config.hist_backend -> GBDT._resolved_hist_backend wiring."""

    def _booster(self, **over):
        import lightgbm_tpu as lgb
        rng = np.random.RandomState(3)
        X = rng.randn(300, 4).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        params = {"objective": "binary", "num_leaves": 7,
                  "max_bin": 31, "verbosity": -1, "min_data_in_leaf": 5,
                  "use_quantized_grad": True, **over}
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        return lgb.Booster(params=params, train_set=ds)

    def test_config_rejects_unknown_backend(self):
        with pytest.raises(Exception):
            self._booster(hist_backend="vliw")

    def test_auto_pins_mxu_on_cpu(self):
        from lightgbm_tpu.observability import registry
        registry.reset()
        bst = self._booster(hist_backend="auto")
        g = bst.gbdt
        g._hist_impl = "mxu"
        assert g._resolved_hist_backend() == "mxu"
        assert g._hist_autotune == {"choice": "mxu", "autotuned": False,
                                    "timings_ms": {}}
        snap = registry.hist_backend_snapshot()
        assert snap["choice"] == "mxu" and snap["is_mxu"] == 1
        assert "lightgbm_tpu_hist_backend_is_mxu 1" in \
            registry.prometheus_text()

    def test_forced_backend_reaches_grow_kwargs(self):
        bst = self._booster(hist_backend="pallas")
        g = bst.gbdt
        g._hist_impl = "mxu"
        assert g._mxu_grow_kwargs()["hist_backend"] == "pallas"
        # pinned: a second resolution returns the cache
        assert g._resolved_hist_backend() == "pallas"

    def test_autotune_all_failures_fall_back_to_mxu(self):
        # on CPU the non-interpret kernels cannot run: both timings come
        # back inf and the choice must degrade to mxu, not raise
        from lightgbm_tpu.learner.grower_mxu import autotune_hist_backend
        bins = jnp.asarray(np.random.RandomState(0).randint(
            0, 15, size=(256, 4)).astype(np.uint8))
        choice, timings = autotune_hist_backend(bins, num_slots=4,
                                                bmax=15)
        assert choice == "mxu"
        assert set(timings) == {"mxu", "pallas"}
        assert all(t == float("inf") for t in timings.values())

    def test_fused_rejects_unresolved_auto(self):
        from lightgbm_tpu.boosting.fused import build_fused_train
        with pytest.raises(ValueError, match="resolved hist_backend"):
            build_fused_train(
                objective=None, bins=None, cnt_weight=None,
                feature_mask_fn=None, num_bins=None,
                missing_is_nan=None, is_cat=None,
                grower_kwargs={"hist_backend": "auto"}, shrinkage=0.1,
                extra_seed=0, needs_rng=False)


# ----------------------------------------------------------------------
# tree/model byte parity through the boosters (interpret mode: minutes)
def _strip_backend_echo(model_str):
    """model.txt records every param, including hist_backend itself —
    the one line that legitimately differs across backends."""
    return "\n".join(l for l in model_str.splitlines()
                     if not l.startswith("[hist_backend:"))


@pytest.mark.slow
class TestModelByteParity:
    def _train(self, objective, hist_backend, num_class=1, seed=7):
        import lightgbm_tpu as lgb
        rng = np.random.RandomState(seed)
        X = rng.randn(500, 5).astype(np.float32)
        if num_class > 1:
            y = rng.randint(0, num_class, size=500).astype(np.float32)
        elif objective == "regression":
            y = (X[:, 0] + 0.3 * rng.randn(500)).astype(np.float32)
        else:
            y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
        params = {"objective": objective, "num_leaves": 7,
                  "learning_rate": 0.2, "max_bin": 31, "verbosity": -1,
                  "min_data_in_leaf": 5, "use_quantized_grad": True,
                  "hist_backend": hist_backend}
        if num_class > 1:
            params["num_class"] = num_class
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        bst = lgb.Booster(params=params, train_set=ds)
        bst.update()
        g = bst.gbdt
        g._hist_impl = "mxu"
        g._mxu_interpret = True
        g._fused_run = None
        g._hist_backend = None   # re-resolve on the forced MXU path
        for _ in range(3):
            bst.update()
        return _strip_backend_echo(bst.model_to_string())

    @pytest.mark.parametrize("objective,num_class", [
        ("regression", 1), ("binary", 1), ("multiclass", 3)])
    def test_byte_identical_across_backends(self, objective, num_class):
        ref = self._train(objective, "mxu", num_class)
        for hb in ("pallas", "scatter"):
            got = self._train(objective, hb, num_class)
            assert got == ref, f"{objective}: {hb} differs from mxu"
