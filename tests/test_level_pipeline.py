"""Level-pipelined grower: parity oracle, compile-count guard,
overlap accounting (`make perf`).

The staged driver (learner/grower_pipeline.py) dispatches the passes of
the shared growth core — ``_make_grow_core``, the same core the
monolithic ``grow_tree_mxu`` traces, collective psum site included —
as separate stage programs with speculative host-side fixup dispatch.
Three contracts are pinned here:

- **byte parity**: ``grow_tree_pipelined`` output is bit-for-bit the
  monolith's, per-tree (slow tier: tobytes over every TreeArrays field
  — NaN leaf values compare equal as bytes) and per-model (slow tier:
  byte-equal model.txt across regression/binary/multiclass); tier-1
  keeps the cheap lookahead-invariance byte check (the monolith oracle
  is a second ~10s interpret-mode compile);
- **compile bound**: distinct stage programs per (shape, config) ==
  ``growth_plan(...).n_stage_programs``, each compiling EXACTLY once —
  a shape leak that recompiled per level or per tree would show up as
  compiles > 1 in the ``grow_stage_*`` compile-accounting entries;
- **overlap accounting**: LevelPipelineStats counts (stages, fixup
  dispatch, speculative lower bound, early stop) obey the dispatch
  algebra — count-based, no wall-clock thresholds.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.data import BinnedDataset, Metadata
from lightgbm_tpu.learner.grower_mxu import (_make_grow_core,  # noqa: F401
                                             grow_tree_mxu, growth_plan)
from lightgbm_tpu.learner.grower_pipeline import (LevelPipelineStats,
                                                  grow_tree_pipelined)
from lightgbm_tpu.learner.split import SplitHyperParams
from lightgbm_tpu.observability import registry as _obs


def _inputs(n=384, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    ds = BinnedDataset.from_raw(X, Metadata(n, label=y), max_bin=15)
    g = jnp.asarray(0.5 - y + 0.01 * rng.randn(n).astype(np.float32))
    h = jnp.full(n, 0.25, jnp.float32)
    return (jnp.asarray(ds.bins), g, h, jnp.ones(n, jnp.float32),
            jnp.ones(ds.num_features, jnp.float32),
            jnp.asarray(ds.num_bins), jnp.asarray(ds.missing_types == 2),
            jnp.asarray(ds.is_categorical))


# interpret-mode programs cost ~10s each to compile on one CPU core, so
# every default-tier test in this file shares ONE (shape, config) cell —
# _inputs() shapes + _KW — and only the data (seed) varies: the compile
# guard below runs first and pays the stage-set compile once, everything
# after it is cache hits plus at most one distinct monolith program.
_KW = dict(num_leaves=7, max_depth=0,
           hp=SplitHyperParams(min_data_in_leaf=20), bmax=15,
           interpret=True)


def _assert_bytes_equal(out_a, out_b):
    t_a, r_a = out_a[0], out_a[1]
    t_b, r_b = out_b[0], out_b[1]
    for fld, x, y in zip(t_a._fields, t_a, t_b):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes(), fld
    assert np.asarray(r_a).tobytes() == np.asarray(r_b).tobytes()
    for x, y in zip(out_a[2:], out_b[2:]):
        for xi, yi in zip(x, y):
            assert np.asarray(xi).tobytes() == np.asarray(yi).tobytes()


def test_compile_count_bounded_and_no_shape_leak():
    # FIRST test in the file: the shared cell's _stage jit cache must
    # be cold here so compiles are attributable (no other tier-1 file
    # touches grower_pipeline)
    args_a = _inputs(seed=1)
    kw = _KW
    plan = growth_plan(num_leaves=kw["num_leaves"])
    _obs.compiles.reset()

    grow_tree_pipelined(*args_a, lookahead=2, **kw)
    snap = {k: v for k, v in _obs.compiles.snapshot().items()
            if k.startswith("grow_stage_")}
    assert len(snap) == plan.n_stage_programs
    assert set(snap) == ({"grow_stage_init", "grow_stage_bridge",
                          "grow_stage_fixup", "grow_stage_final"} |
                         {f"grow_stage_pass_{p}"
                          for p in range(len(plan.schedule))})
    # one compiled program per entry — the fixup program is compiled
    # once and re-dispatched with a traced iteration index
    for entry, rec in snap.items():
        assert rec["compiles"] == 1, (entry, rec)


def test_fixup_program_retrace_stable():
    # shape-leak guard, checked at the trace level instead of by
    # re-dispatching the whole pipeline: the fixup stage's jaxpr must
    # be identical across iteration indices — the retrace_stable
    # helper the TRACE005 lint contract runs over the production
    # manifest. If `it` (or any value derived from it) were baked into
    # the program, each fixup dispatch would recompile and the compile
    # bound above would be a lie. Traces only: nothing executes.
    import functools

    import jax

    from lightgbm_tpu.analysis.tracecheck import retrace_stable
    from lightgbm_tpu.learner import grower_pipeline as gp

    names = ("bins", "grad", "hess", "cnt_weight", "feature_mask",
             "num_bins", "missing_is_nan", "is_cat_feat")
    base = dict(zip(names, _inputs(seed=1)))
    state0, quant0 = jax.eval_shape(
        functools.partial(gp._stage, stage="init", **_KW), **base)
    argsets = [dict(base, stage="fixup", state=state0,
                    quant_state=quant0,
                    it=jnp.asarray(i, jnp.int32), **_KW)
               for i in (3, 9)]
    assert retrace_stable(gp._stage, argsets)


# slow tier: the monolith oracle is a SECOND ~10s interpret-mode
# compile on top of the stage set; tier-1 keeps the compile guard and
# the lookahead-invariance byte check below, while oracle parity runs
# here per-tree and (further down) at model.txt level per objective
@pytest.mark.slow
def test_pipelined_matches_monolith_bytes():
    args = _inputs()
    _assert_bytes_equal(grow_tree_pipelined(*args, lookahead=2, **_KW),
                        grow_tree_mxu(*args, **_KW))


@pytest.mark.perf
class TestOverlapAccounting:
    """Dispatch algebra for the speculative fixup overlap — the
    structure behind the round-6 numbers, count-based only."""

    def test_stage_and_fixup_counts(self):
        args = _inputs(seed=3)
        plan = growth_plan(num_leaves=_KW["num_leaves"])
        stats = LevelPipelineStats()
        grow_tree_pipelined(*args, lookahead=2, stats=stats, **_KW)
        assert stats.fallback is None
        # init + schedule passes + bridge + fixups + final
        assert stats.stages == (len(plan.schedule) + 3 +
                                stats.fixup_dispatched)
        assert 0 <= stats.fixup_speculative <= stats.fixup_dispatched
        assert stats.fixup_dispatched <= plan.max_fixup_dispatch
        assert stats.entries[0] == "grow_stage_init"
        assert stats.entries[-1] == "grow_stage_final"
        assert stats.lookahead == 2
        assert stats.wall_seconds > 0.0

    def test_early_stop_counts_speculative_fixups(self):
        # the tree completes well inside the doubling schedule, so the
        # done flag is set long before max_fixup_dispatch, the lagged
        # poll sees it, and every fixup chunk dispatched past it is
        # known-speculative
        args = _inputs(seed=4)
        plan = growth_plan(num_leaves=_KW["num_leaves"])
        assert plan.max_fixup_dispatch >= 2   # else nothing to stop
        stats = LevelPipelineStats()
        out_p = grow_tree_pipelined(*args, lookahead=1, stats=stats,
                                    **_KW)
        assert stats.stopped_early
        assert stats.fixup_speculative >= 1
        assert stats.fixup_dispatched < plan.max_fixup_dispatch
        assert stats.done_polls >= 1
        # speculative dispatch past done is an identity no-op: the
        # result is invariant under how much the driver speculates
        # (lookahead changes the dispatch pattern, not one byte of the
        # tree; the slow tier pins the same bytes against the monolith)
        _assert_bytes_equal(out_p,
                            grow_tree_pipelined(*args, lookahead=3,
                                                **_KW))

    def test_debug_info_falls_back_to_monolith(self, monkeypatch):
        # debug_info's fixup-iteration count is a device while_loop
        # artifact — the staged driver hands the whole tree to the
        # monolithic oracle, untouched and verbatim (parity is by
        # construction: the fallback IS the monolith call, so stub it
        # out rather than pay its ~10s interpret-mode compile here)
        from lightgbm_tpu.learner import grower_pipeline as gp
        seen = {}

        def spy(*args, **kw):
            seen["args"], seen["kw"] = args, kw
            return "monolith-output"

        monkeypatch.setattr(gp, "grow_tree_mxu", spy)
        args = _inputs(seed=5)
        kw = dict(debug_info=True, **_KW)
        stats = LevelPipelineStats()
        out_p = grow_tree_pipelined(*args, stats=stats, **kw)
        assert out_p == "monolith-output"
        assert stats.fallback == "debug_info"
        assert stats.stages == 0
        assert seen["args"] == tuple(args)
        assert seen["kw"].get("debug_info") is True
        for key, val in _KW.items():
            assert seen["kw"][key] == val, key

    def test_growth_plan_program_bound(self):
        # the static plan both drivers share: program count and fixup
        # dispatch bound are pure functions of the config
        for nl, over, gate in ((31, 1.15, 0.9), (7, 0.0, 0.0),
                               (127, 0.0, 0.0)):
            plan = growth_plan(num_leaves=nl, overshoot=over,
                               bridge_gate=gate)
            assert plan.n_stage_programs == len(plan.schedule) + 4
            assert plan.max_fixup_dispatch == max(
                0, plan.L_g - len(plan.schedule) - 1)
            assert plan.s_max == plan.L_g + 1


@pytest.mark.slow
class TestModelByteParity:
    """level_pipeline=true must be invisible in the trained model:
    byte-equal model.txt across objectives (the monolithic grower is
    the retained oracle)."""

    OBJECTIVES = (
        ("regression", 1, "l2"),
        ("binary", 1, "binary"),
        ("multiclass", 3, "multiclass"),
    )

    def _train(self, objective, num_class, level_pipeline):
        import lightgbm_tpu as lgb
        rng = np.random.RandomState(11)
        X = rng.randn(400, 5).astype(np.float32)
        if objective == "multiclass":
            y = rng.randint(0, num_class, 400).astype(np.float32)
        elif objective == "binary":
            y = (X[:, 0] > 0).astype(np.float32)
        else:
            y = (X[:, 0] + 0.3 * rng.randn(400)).astype(np.float32)
        params = {"objective": objective, "num_leaves": 7,
                  "learning_rate": 0.2, "max_bin": 31, "verbosity": -1,
                  "min_data_in_leaf": 5,
                  "level_pipeline": level_pipeline,
                  "level_pipeline_lookahead": 2}
        if objective == "multiclass":
            params["num_class"] = num_class
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        bst = lgb.Booster(params=params, train_set=ds)
        bst.update()
        g = bst.gbdt
        g._hist_impl = "mxu"
        g._mxu_interpret = True
        g._fused_run = None
        for _ in range(3):
            bst.update()
        return "\n".join(
            ln for ln in bst.model_to_string().splitlines()
            if not ln.startswith("[level_pipeline"))

    @pytest.mark.parametrize("objective,num_class,_name", OBJECTIVES,
                             ids=[o[2] for o in OBJECTIVES])
    def test_byte_identical_models(self, objective, num_class, _name):
        on = self._train(objective, num_class, True)
        off = self._train(objective, num_class, False)
        assert on == off
