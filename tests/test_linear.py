"""Linear-tree tests (reference test_engine.py linear-tree section;
LinearTreeLearner, src/treelearner/linear_tree_learner.cpp)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _piecewise_linear(n=4000, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, 4).astype(np.float32)
    y = (np.where(X[:, 0] > 0, 2.0 * X[:, 1] + 1.0, -1.5 * X[:, 1]) +
         0.05 * r.randn(n)).astype(np.float32)
    return X, y


PARAMS = {"objective": "regression", "num_leaves": 8, "verbosity": -1,
          "learning_rate": 0.3}


class TestLinearTree:
    def test_beats_constant_leaves_on_piecewise_linear(self):
        X, y = _piecewise_linear()
        b0 = lgb.train(PARAMS, lgb.Dataset(X, label=y), 40)
        b1 = lgb.train({**PARAMS, "linear_tree": True},
                       lgb.Dataset(X, label=y), 40)
        mse0 = np.mean((b0.predict(X) - y) ** 2)
        mse1 = np.mean((b1.predict(X) - y) ** 2)
        assert mse1 < mse0 * 0.5

    def test_model_text_round_trip(self):
        X, y = _piecewise_linear()
        b1 = lgb.train({**PARAMS, "linear_tree": True},
                       lgb.Dataset(X, label=y), 10)
        s = b1.model_to_string()
        assert "is_linear=1" in s
        assert "leaf_const=" in s and "leaf_coeff=" in s \
            and "num_features=" in s
        b2 = lgb.Booster(model_str=s)
        np.testing.assert_allclose(b1.predict(X), b2.predict(X), rtol=1e-5)

    def test_leaf_models_use_path_features_only(self):
        X, y = _piecewise_linear()
        b = lgb.train({**PARAMS, "num_leaves": 2, "learning_rate": 1.0,
                       "linear_tree": True}, lgb.Dataset(X, label=y), 1)
        root = b.dump_model()["tree_info"][0]["tree_structure"]
        split_feat = root["split_feature"]
        for side in ("left_child", "right_child"):
            for f in root[side]["leaf_features"]:
                assert f == split_feat

    def test_nan_rows_fall_back_to_constant(self):
        X, y = _piecewise_linear()
        b = lgb.train({**PARAMS, "linear_tree": True},
                      lgb.Dataset(X, label=y), 10)
        Xn = X[:20].copy()
        Xn[:, :] = np.nan
        p = b.predict(Xn)
        assert np.isfinite(p).all()
        # all-NaN rows all route the same way -> one constant prediction
        assert np.allclose(p, p[0])

    def test_valid_set_eval(self):
        X, y = _piecewise_linear()
        Xv, yv = _piecewise_linear(seed=1)
        ev = {}
        lgb.train({**PARAMS, "linear_tree": True, "metric": "l2"},
                  lgb.Dataset(X, label=y), 30,
                  valid_sets=[lgb.Dataset(Xv, label=yv)],
                  valid_names=["v"],
                  callbacks=[lgb.record_evaluation(ev)])
        l2 = ev["v"]["l2"]
        assert l2[-1] < l2[0] * 0.3

    def test_linear_binary_classification(self):
        r = np.random.RandomState(2)
        X = r.randn(3000, 5).astype(np.float32)
        y = ((X[:, 0] * 1.5 + X[:, 1] > 0)).astype(np.float32)
        b = lgb.train({"objective": "binary", "linear_tree": True,
                       "num_leaves": 8, "verbosity": -1},
                      lgb.Dataset(X, label=y), 20)
        acc = np.mean((b.predict(X) > 0.5) == y)
        assert acc > 0.93

    def test_refit_linear(self):
        X, y = _piecewise_linear()
        X2, y2 = _piecewise_linear(seed=3)
        b = lgb.train({**PARAMS, "linear_tree": True},
                      lgb.Dataset(X, label=y), 10)
        b2 = b.refit(X2, y2, decay_rate=0.5)
        mse = np.mean((b2.predict(X2) - y2) ** 2)
        assert mse < np.var(y2) * 0.5

    def test_goss_conflict_raises(self):
        X, y = _piecewise_linear(n=500)
        with pytest.raises(ValueError):
            lgb.train({**PARAMS, "linear_tree": True, "boosting": "goss"},
                      lgb.Dataset(X, label=y), 2)

    def test_l1_objective_conflict_raises(self):
        X, y = _piecewise_linear(n=500)
        with pytest.raises(ValueError):
            lgb.train({**PARAMS, "objective": "regression_l1",
                       "linear_tree": True}, lgb.Dataset(X, label=y), 2)

    def test_pred_contrib_unsupported(self):
        X, y = _piecewise_linear(n=500)
        b = lgb.train({**PARAMS, "linear_tree": True},
                      lgb.Dataset(X, label=y), 3)
        with pytest.raises(NotImplementedError):
            b.predict(X, pred_contrib=True)

    def test_dart_linear(self):
        X, y = _piecewise_linear()
        b = lgb.train({**PARAMS, "linear_tree": True, "boosting": "dart",
                       "drop_rate": 0.3, "seed": 4},
                      lgb.Dataset(X, label=y), 25)
        mse = np.mean((b.predict(X) - y) ** 2)
        assert mse < np.var(y) * 0.3

    def test_binary_cache_keeps_raw(self, tmp_path):
        X, y = _piecewise_linear()
        fn = str(tmp_path / "d.bin")
        ds = lgb.Dataset(X, label=y, params={"linear_tree": True})
        ds.construct()
        ds.save_binary(fn)
        b = lgb.train({**PARAMS, "linear_tree": True},
                      lgb.Dataset(fn), 10)
        assert "is_linear=1" in b.model_to_string()
