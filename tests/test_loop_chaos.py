"""Continuous-loop chaos matrix (docs/Continuous.md, "Chaos protocol").

The ISSUE-14 acceptance run, driven by `testing.chaos_loop` and marked
slow (`make loop-chaos`): one unkilled reference run records every
published generation's bytes, then the loop is killed at each fault
site on the cycle path — ingest, train, generation cut, both sides of
the serving swap, and the torn-publish window — while closed-loop
traffic hammers the served entry. Every scenario must show:

- zero dropped serve requests (every request in the ledger resolved);
- every answer bit-identical to the host predict of SOME published
  generation (the dyadic publish transform makes device f32 sums equal
  host f64 sums, so equality is exact, not a tolerance);
- every published generation — and the final live model — byte-
  identical to the unkilled reference run;
- at least one fault fired and one cycle failure was counted, with a
  flushed flight-recorder postmortem per failed cycle.

Poison-window quarantine and the freshness SLO alarm are then
demonstrated from the metric family alone (no internal state reads).
"""

import os
import shutil

import numpy as np
import pytest

from lightgbm_tpu.observability import registry as _obs
from lightgbm_tpu.reliability import faults
from lightgbm_tpu.testing.chaos_loop import (run_loop_scenario,
                                             verify_survivor_answers,
                                             write_stream_csv)

pytestmark = [pytest.mark.loop, pytest.mark.slow]

WINDOWS = 3
N_REQUESTS = 120

#: (site, schedule) — schedules are tuned to the in-process recovery
#: ladders in front of each site so the fault actually kills the
#: cycle: `histogram_build` sits inside retry_call(attempts=3), so 3
#: consecutive failures are needed (skip=2 lands them mid-train, after
#: two per-iteration checkpoints); `checkpoint_io` skips the 3
#: callback saves (loop_rounds=3, swallowed by the callback) so the
#: failure lands on the generation cut itself.
KILL_MATRIX = [
    ("streaming_ingest", {"skip": 1, "fail": 1}),
    ("histogram_build", {"skip": 2, "fail": 3}),
    ("checkpoint_io", {"skip": 3, "fail": 1}),
    ("serving_hot_swap", {"fail": 1}),
    ("serving_hot_swap_commit", {"fail": 1}),
    ("loop_publish", {"fail": 1}),
]


@pytest.fixture(scope="module")
def chaos_env(tmp_path_factory):
    """Shared stream + ONE unkilled reference run. The reference and
    every kill scenario reuse the same loop_dir path (wiped between
    runs): the dir name is embedded in the model's parameters dump, so
    byte-identity requires path equality, not just tree equality."""
    root = tmp_path_factory.mktemp("loop_chaos")
    data = str(root / "stream.csv")
    X = write_stream_csv(data, chunks=6, chunk_rows=48, f=6)
    loop_dir = str(root / "loop")
    faults.clear()
    ref = run_loop_scenario(data, loop_dir, X, windows=WINDOWS)
    assert ref.bootstrap_published + ref.published == WINDOWS
    assert sorted(ref.gen_models) == [1, 2, 3]
    shutil.rmtree(loop_dir)
    return data, loop_dir, X, ref


@pytest.fixture(autouse=True)
def _clean(chaos_env):
    faults.clear()
    _obs.reset()
    shutil.rmtree(chaos_env[1], ignore_errors=True)
    yield
    faults.clear()


@pytest.mark.parametrize("site,sched", KILL_MATRIX,
                         ids=[s for s, _ in KILL_MATRIX])
def test_kill_at_site_survives_under_live_traffic(chaos_env, site,
                                                  sched):
    data, loop_dir, X, ref = chaos_env
    out = run_loop_scenario(data, loop_dir, X, windows=WINDOWS,
                            site=site, n_requests=N_REQUESTS, **sched)
    # the kill actually happened and was survived
    assert out.trips >= 1, f"{site}: fault never fired"
    assert out.cycle_failures >= 1, f"{site}: no cycle died"
    assert out.bootstrap_published + out.published == WINDOWS
    # zero dropped serve requests; nothing shed, nothing hung
    assert out.load.dropped == 0
    assert set(out.load.by_outcome()) == {"ok"}, out.load.by_outcome()
    # every answer bit-identical to a real published generation
    assert verify_survivor_answers(out.load, out.gen_models, X) \
        == N_REQUESTS
    # every generation — and the final live model — byte-identical to
    # the unkilled reference
    assert sorted(out.gen_models) == sorted(ref.gen_models)
    for gen, model in ref.gen_models.items():
        assert out.gen_models[gen] == model, \
            f"{site}: generation {gen} diverged from unkilled run"
    assert out.final_model == ref.final_model
    # a flushed postmortem per failed cycle
    assert len(out.postmortems) >= out.cycle_failures
    assert out.quarantined == []


def test_poison_window_quarantine_visible_from_metrics_alone(chaos_env):
    """Window 2's every rebuild attempt dies (fail budget == the full
    poison retry budget): it must be quarantined and the loop must
    keep publishing — all observed via lightgbm_tpu_freshness."""
    data, loop_dir, X, ref = chaos_env
    out = run_loop_scenario(data, loop_dir, X, windows=WINDOWS,
                            site="streaming_ingest", fail=3)
    assert out.cycle_failures == 3
    assert out.bootstrap_published + out.published == 2   # window 2 lost
    # the metric family alone tells the story: publishes kept flowing,
    # one window quarantined, generation advanced past the poison
    txt = _obs.prometheus_text()
    assert "lightgbm_tpu_freshness_quarantined_windows 1" in txt
    assert "lightgbm_tpu_freshness_generation 2" in txt
    assert "lightgbm_tpu_freshness_publishes 2" in txt
    f = out.freshness
    assert f["quarantined_windows"] == 1 and f["generation"] == 2
    # published generations still match the reference prefix: gen 1
    # bytes are identical; gen 2 trained on window 3's rows instead
    assert out.gen_models[1] == ref.gen_models[1]
    assert out.gen_models[2] != ref.gen_models[2]
    assert len(out.postmortems) >= 3


def test_freshness_slo_alarm_fires_from_metrics_alone(chaos_env):
    """A sub-nanosecond staleness SLO must trip the alarm gauge on
    every publish — no faults involved, pure watchdog."""
    data, loop_dir, X, _ref = chaos_env
    out = run_loop_scenario(
        data, loop_dir, X, windows=2,
        params_overrides={"loop_freshness_slo_s": 1e-9})
    assert out.cycle_failures == 0
    f = out.freshness
    assert f["slo_alarm"] == 1 and f["slo_breaches"] == 2
    assert f["staleness_slo_s"] == 1e-9
    txt = _obs.prometheus_text()
    assert "lightgbm_tpu_freshness_slo_alarm 1" in txt
    assert "lightgbm_tpu_freshness_slo_breaches 2" in txt
