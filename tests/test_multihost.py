"""Two-process localhost multi-machine training (reference
tests/distributed/_test_distributed.py: N CLI processes over loopback
sockets; here N python processes joined by jax.distributed, each holding
its row partition, with histogram psums spanning both)."""

import os
import sys
import textwrap

import numpy as np
import pytest

from lightgbm_tpu.testing.subproc import (free_port, rank_env,
                                          run_ranks)

pytestmark = pytest.mark.slow  # spawns processes, compiles twice


def _assert_all_ok(results, what):
    """Shared post-mortem for a 2-rank launch: fail loudly on timeout
    (children already killed by run_ranks) or non-zero exit."""
    if any(r.timed_out for r in results):
        pytest.fail(f"{what} timed out")
    for r in results:
        assert r.returncode == 0, f"rank {r.rank}: {r.tail()}"

_WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np
    sys.path.insert(0, {repo!r})
    rank = int(os.environ["LIGHTGBM_TPU_MACHINE_RANK"])
    ports = os.environ["TEST_PORTS"].split(",")
    import lightgbm_tpu as lgb
    # network init BEFORE any data/backend work, like the reference CLI
    lgb.setup_multihost(
        2, ",".join(f"127.0.0.1:{{p}}" for p in ports),
        local_listen_port=int(ports[rank]))
    from conftest_data import make_data
    X, y = make_data()
    cut = len(y) // 2 + int(os.environ.get("TEST_UNEVEN", "0"))
    sl = slice(0, cut) if rank == 0 else slice(cut, None)
    objective = os.environ.get("TEST_OBJECTIVE", "binary")
    params = dict(objective=objective, tree_learner="data",
                  num_machines=2,
                  machines=",".join(f"127.0.0.1:{{p}}" for p in ports),
                  local_listen_port=int(ports[rank]),
                  num_leaves=15, verbosity=-1, min_data_in_leaf=20,
                  boost_from_average=False)
    bst = lgb.train(params, lgb.Dataset(X[sl], label=y[sl]), 5)
    bst.save_model(os.environ["TEST_OUT"])
""")

_DATA_MOD = textwrap.dedent("""
    import numpy as np
    def make_data(n=4096, f=8, seed=3):
        r = np.random.RandomState(seed)
        X = r.randn(n, f)
        logit = X[:, 0] * 1.5 + 0.5 * X[:, 1] ** 2 - X[:, 2] + \\
            0.3 * r.randn(n)
        y = (logit > np.median(logit)).astype(np.float32)
        return X, y
""")


@pytest.mark.parametrize("uneven", [0, 17])
def test_two_process_matches_single_process(tmp_path, uneven):
    _run_two_process(tmp_path, uneven, "binary", exact=True)


def test_two_process_l1_renew_sync(tmp_path):
    # L1-family objectives renew leaves from percentiles; multi-machine
    # averages per-rank renewed values (serial_tree_learner.cpp:747-757)
    # — ranks must agree exactly, single-process parity is approximate
    # (the reference has the same mean-of-local-percentiles semantics)
    _run_two_process(tmp_path, 0, "regression_l1", exact=False)


def _run_two_process(tmp_path, uneven, objective, exact):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    (tmp_path / "conftest_data.py").write_text(_DATA_MOD)
    (tmp_path / "worker.py").write_text(_WORKER.format(repo=repo))
    ports = [str(free_port()), str(free_port())]
    outs = [tmp_path / f"model_{rank}.txt" for rank in range(2)]
    results = run_ranks(
        [[sys.executable, str(tmp_path / "worker.py")]
         for _ in range(2)],
        envs=[rank_env(rank,
                       TEST_PORTS=",".join(ports),
                       TEST_OUT=str(outs[rank]),
                       TEST_UNEVEN=str(uneven),
                       TEST_OBJECTIVE=objective,
                       PYTHONPATH=str(tmp_path))
              for rank in range(2)],
        cwd=str(tmp_path))
    _assert_all_ok(results, "multi-process training")

    # both ranks hold the identical replicated model (the dumped
    # parameters section records each rank's own listen port — the only
    # legitimate difference)
    def strip_port(text):
        return "\n".join(ln for ln in text.splitlines()
                         if "local_listen_port" not in ln)

    m0 = outs[0].read_text()
    m1 = outs[1].read_text()
    assert strip_port(m0) == strip_port(m1)

    # and it equals single-process training on the concatenated data
    import lightgbm_tpu as lgb
    # each test writes its own conftest_data.py variant; drop any cached
    # module from an earlier test's tmp dir or the import is shadowed
    sys.modules.pop("conftest_data", None)
    sys.path.insert(0, str(tmp_path))
    try:
        from conftest_data import make_data
    finally:
        sys.path.pop(0)
    X, y = make_data()
    bst = lgb.train(dict(objective=objective, tree_learner="data",
                         num_leaves=15, verbosity=-1, min_data_in_leaf=20,
                         boost_from_average=False),
                    lgb.Dataset(X, label=y), 5)
    multi = lgb.Booster(model_str=m0)
    if exact:
        np.testing.assert_allclose(multi.predict(X[:512]),
                                   bst.predict(X[:512]),
                                   rtol=1e-5, atol=1e-6)
    else:
        a, b = multi.predict(X[:512]), bst.predict(X[:512])
        # mean-of-local-percentiles vs global percentile: approximate by
        # design (like the reference); rank equality above is the hard
        # guarantee
        assert np.corrcoef(a, b)[0, 1] > 0.9
        assert np.mean(np.abs(a - b)) < 0.15


def test_cli_shared_file_two_process(tmp_path):
    """CLI multi-machine flow (reference CLI + mlist: the distributed
    mockup of _test_distributed.py): both processes read the SAME csv,
    pre_partition=false assigns contiguous row blocks per rank, and the
    saved models match single-process training."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = np.random.RandomState(5)
    n = 3000
    X = r.randn(n, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    np.savetxt(tmp_path / "train.csv",
               np.column_stack([y, X]), delimiter=",", fmt="%.7f")
    ports = [str(free_port()), str(free_port())]
    machines = ",".join(f"127.0.0.1:{p}" for p in ports)
    outs = [tmp_path / f"cli_model_{rank}.txt" for rank in range(2)]
    results = run_ranks(
        [[sys.executable, "-m", "lightgbm_tpu.cli",
          "task=train", f"data={tmp_path / 'train.csv'}",
          "label_column=0", "objective=binary", "num_iterations=5",
          "num_leaves=15", "min_data_in_leaf=20", "verbosity=-1",
          "boost_from_average=false", "tree_learner=data",
          "num_machines=2", f"machines={machines}",
          f"local_listen_port={ports[rank]}",
          f"output_model={outs[rank]}"]
         for rank in range(2)],
        envs=[rank_env(rank, PYTHONPATH=repo) for rank in range(2)],
        cwd=str(tmp_path))
    _assert_all_ok(results, "CLI multi-process training")

    import lightgbm_tpu as lgb
    m0 = lgb.Booster(model_file=str(outs[0]))
    m1 = lgb.Booster(model_file=str(outs[1]))
    single = lgb.train(dict(objective="binary", num_leaves=15,
                            verbosity=-1, min_data_in_leaf=20,
                            boost_from_average=False,
                            tree_learner="data"),
                       lgb.Dataset(X, label=y), 5)
    np.testing.assert_allclose(m0.predict(X[:400]), m1.predict(X[:400]),
                               rtol=1e-7, atol=1e-8)
    np.testing.assert_allclose(m0.predict(X[:400]),
                               single.predict(X[:400]),
                               rtol=1e-5, atol=1e-6)


_WORKER_SEQ = textwrap.dedent("""
    import os, sys
    import numpy as np
    sys.path.insert(0, {repo!r})
    rank = int(os.environ["LIGHTGBM_TPU_MACHINE_RANK"])
    ports = os.environ["TEST_PORTS"].split(",")
    import lightgbm_tpu as lgb
    lgb.setup_multihost(
        2, ",".join(f"127.0.0.1:{{p}}" for p in ports),
        local_listen_port=int(ports[rank]))
    from conftest_data import make_data
    X, y = make_data()
    cut = len(y) // 2
    sl = slice(0, cut) if rank == 0 else slice(cut, None)
    Xl, yl = X[sl], y[sl]

    class Seq(lgb.Sequence):
        batch_size = 512
        def __init__(self, a): self.a = a
        def __getitem__(self, i): return self.a[i]
        def __len__(self): return len(self.a)

    data = Seq(Xl) if os.environ["TEST_INPUT"] == "seq" else Xl
    params = dict(objective="binary", tree_learner="data",
                  num_machines=2,
                  machines=",".join(f"127.0.0.1:{{p}}" for p in ports),
                  local_listen_port=int(ports[rank]),
                  num_leaves=15, verbosity=-1, min_data_in_leaf=20,
                  boost_from_average=False)
    bst = lgb.train(params, lgb.Dataset(data, label=yl), 5)
    bst.save_model(os.environ["TEST_OUT"])
""")


def test_two_process_sequence_input_matches_array_input(tmp_path):
    """Streamed (Sequence) input under multi-machine training: the
    per-rank chunk sample rides the same mapper allgather as arrays
    (reference dataset_loader.cpp:722-807 works from any local
    iterator), so the resulting model must be identical to array
    input."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    (tmp_path / "conftest_data.py").write_text(_DATA_MOD)
    (tmp_path / "worker.py").write_text(_WORKER_SEQ.format(repo=repo))
    models = {}
    for mode in ("array", "seq"):
        ports = [str(free_port()), str(free_port())]
        outs = [tmp_path / f"model_{mode}_{rank}.txt"
                for rank in range(2)]
        results = run_ranks(
            [[sys.executable, str(tmp_path / "worker.py")]
             for _ in range(2)],
            envs=[rank_env(rank,
                           TEST_PORTS=",".join(ports),
                           TEST_OUT=str(outs[rank]),
                           TEST_INPUT=mode,
                           PYTHONPATH=str(tmp_path))
                  for rank in range(2)],
            cwd=str(tmp_path))
        _assert_all_ok(results, f"multi-process {mode} training")
        models[mode] = "\n".join(
            ln for ln in outs[0].read_text().splitlines()
            if "local_listen_port" not in ln and "machines" not in ln)
    assert models["array"] == models["seq"]


_WORKER_EFB = textwrap.dedent("""
    import os, sys
    import numpy as np
    sys.path.insert(0, {repo!r})
    rank = int(os.environ["LIGHTGBM_TPU_MACHINE_RANK"])
    ports = os.environ["TEST_PORTS"].split(",")
    import lightgbm_tpu as lgb
    lgb.setup_multihost(
        2, ",".join(f"127.0.0.1:{{p}}" for p in ports),
        local_listen_port=int(ports[rank]))
    from conftest_data import make_sparse_data
    X, y = make_sparse_data()
    cut = len(y) // 2
    sl = slice(0, cut) if rank == 0 else slice(cut, None)
    params = dict(objective="binary", tree_learner="data",
                  num_machines=2,
                  machines=",".join(f"127.0.0.1:{{p}}" for p in ports),
                  local_listen_port=int(ports[rank]),
                  num_leaves=15, verbosity=-1, min_data_in_leaf=20,
                  max_bin=15,  # small bins so 8 features fit one bundle
                  boost_from_average=False)
    bst = lgb.train(params, lgb.Dataset(X[sl], label=y[sl],
                                        params={{"max_bin": 15}}), 5)
    assert bst.gbdt._efb is not None, "EFB did not engage multi-machine"
    bst.save_model(os.environ["TEST_OUT"])
""")

_SPARSE_DATA = textwrap.dedent("""
    import numpy as np
    def make_sparse_data(n=4096, f=24, seed=9):
        # mutually-exclusive sparse features: each row activates one of
        # every 8-feature group (EFB bundles each group into one column)
        r = np.random.RandomState(seed)
        X = np.zeros((n, f))
        for g in range(0, f, 8):
            which = r.randint(g, g + 8, size=n)
            X[np.arange(n), which] = r.rand(n) + 0.5
        logit = X[:, 0] * 2.0 + X[:, 8] - X[:, 16] + 0.3 * r.randn(n)
        y = (logit > np.median(logit)).astype(np.float32)
        return X, y
""")


def test_two_process_efb_matches_single(tmp_path):
    """EFB under multi-machine training: the greedy bundle plan is built
    from an allgathered row sample (identical on every rank, like the
    distributed bin mappers, dataset_loader.cpp:722-807), so ranks grow
    IDENTICAL models — the hard guarantee. Against single-process EFB
    the comparison is approximate: the pooled-sample plan can bundle
    features differently, and the expansion's default-bin
    reconstruction (node_total - segment mass) carries f32 rounding
    that legitimately flips near-tie splits (the reference's
    sample-based distributed construction is approximate the same
    way)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    (tmp_path / "conftest_data.py").write_text(_DATA_MOD + _SPARSE_DATA)
    (tmp_path / "worker.py").write_text(_WORKER_EFB.format(repo=repo))
    ports = [str(free_port()), str(free_port())]
    outs = [tmp_path / f"model_{rank}.txt" for rank in range(2)]
    results = run_ranks(
        [[sys.executable, str(tmp_path / "worker.py")]
         for _ in range(2)],
        envs=[rank_env(rank,
                       TEST_PORTS=",".join(ports),
                       TEST_OUT=str(outs[rank]),
                       PYTHONPATH=str(tmp_path))
              for rank in range(2)],
        cwd=str(tmp_path))
    _assert_all_ok(results, "multi-process EFB training")

    def strip_port(text):
        return "\n".join(ln for ln in text.splitlines()
                         if "local_listen_port" not in ln)

    m0 = outs[0].read_text()
    assert strip_port(m0) == strip_port(outs[1].read_text())

    import lightgbm_tpu as lgb
    sys.modules.pop("conftest_data", None)  # see test_two_process note
    sys.path.insert(0, str(tmp_path))
    try:
        from conftest_data import make_sparse_data
    finally:
        sys.path.pop(0)
    X, y = make_sparse_data()
    single = lgb.train(dict(objective="binary", tree_learner="data",
                            num_leaves=15, verbosity=-1,
                            min_data_in_leaf=20, max_bin=15,
                            boost_from_average=False),
                       lgb.Dataset(X, label=y,
                                   params={"max_bin": 15}), 5)
    multi = lgb.Booster(model_str=m0)
    a, b = multi.predict(X[:512]), single.predict(X[:512])
    assert np.corrcoef(a, b)[0, 1] > 0.98
    assert np.mean(np.abs(a - b)) < 0.05


def test_collective_manifest_entry_points_resolve():
    """tpulint COLL004 registry: every collective entry point in
    COLLECTIVE_MANIFEST must exist and carry a registered fault site.
    The names asserted here are the ones the analyzer cross-checks
    against this file — the host-collective surface of multihost
    training: _allgather_find_mappers / _distributed_bin_mappers /
    _streaming_mapper_sync (distributed bin finding), and the GBDT
    sync points _setup_train, _setup_parallel, _sync_renewed_leaves,
    _boost_from_average; guarded_allgather is the watchdog-bracketed
    choke point they all funnel through, and checkpoint_agree the
    one-int agreement the coordinated checkpoint protocol rides."""
    from lightgbm_tpu.analysis.rules_spmd import COLLECTIVE_MANIFEST
    from lightgbm_tpu.reliability.faults import KNOWN_SITES
    import lightgbm_tpu.basic as basic
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.parallel.comm import (checkpoint_agree,
                                            guarded_allgather)
    from lightgbm_tpu.streaming.loader import build_streamed_dataset
    from lightgbm_tpu.learner.grower import grow_tree
    from lightgbm_tpu.learner.grower_mxu import grow_tree_mxu
    from lightgbm_tpu.learner.histogram_mxu import quantize_gradients

    resolvable = {
        "guarded_allgather": guarded_allgather,
        "checkpoint_agree": checkpoint_agree,
        "_allgather_find_mappers": basic._allgather_find_mappers,
        "_distributed_bin_mappers": basic._distributed_bin_mappers,
        "_streaming_mapper_sync": basic._streaming_mapper_sync,
        "build_streamed_dataset": build_streamed_dataset,
        "_setup_train": GBDT._setup_train,
        "_setup_parallel": GBDT._setup_parallel,
        "_sync_renewed_leaves": GBDT._sync_renewed_leaves,
        "_boost_from_average": GBDT._boost_from_average,
        "grow_tree": grow_tree,
        "grow_tree_mxu": grow_tree_mxu,
        "quantize_gradients": quantize_gradients,
    }
    manifest_fns = {row[2] for row in COLLECTIVE_MANIFEST}
    assert manifest_fns == set(resolvable), (
        "COLLECTIVE_MANIFEST out of sync with the known collective "
        "entry points")
    for _, _, fn, site, mode, tests in COLLECTIVE_MANIFEST:
        assert callable(resolvable[fn])
        assert site in KNOWN_SITES, f"{fn}: unknown fault site {site}"
        assert mode in ("body", "delegate", "dispatch")
        assert tests, f"{fn}: no test file mapped"
