"""Multi-model fused serving tests (docs/Serving.md "Multi-model
packing" / "Continuous batching").

The pack contract under test:

- packed answers are BIT-identical to the member's solo device predict
  (same f32 accumulation order) across heterogeneous objectives and
  adversarial categorical/missing inputs, and bit-identical to host
  predict on dyadic boosters;
- a pack costs at most ``max_compilations(max_bucket)`` fused-kernel
  compilations total, member count notwithstanding (the
  `_packed_fn()._cache_size()` guard);
- the ``slo`` scheduler skip-and-fills around requests that don't fit
  the batch, ``fifo`` stays a strict prefix;
- admission's rows-aware service model cannot death-spiral on a
  poisoned estimate (empty queue always admits) and never counts
  looser-deadline work against a tight incoming request;
- evicting / hot-swapping one member rebuilds the pack for the
  survivors and drains the old queue through host predict exactly
  once per future — under live load, with `serving_pack_predict`
  faults firing, zero requests drop and every answer stays bit-equal
  to a published model.
"""

import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.reliability import faults
from lightgbm_tpu.serving import (DeadlineExceeded, MicroBatcher,
                                  Server, max_compilations)
from lightgbm_tpu.serving.batcher import _ServiceModel
from lightgbm_tpu.serving.multimodel import _packed_fn
from lightgbm_tpu.testing.chaos_serve import (LoadResult,
                                              dyadic_booster,
                                              run_open_loop,
                                              verify_bit_identical)
from tests.conftest import make_binary, make_multiclass, make_regression

RTOL, ATOL = 1e-5, 1e-7


def _train(objective="binary", n=400, f=8, seed=0, rounds=8):
    if objective == "multiclass":
        X, y = make_multiclass(n=n, f=f, k=3, seed=seed)
        params = {"objective": "multiclass", "num_class": 3}
    elif objective == "regression":
        X, y = make_regression(n=n, f=f, seed=seed)
        params = {"objective": "regression"}
    else:
        X, y = make_binary(n=n, f=f, seed=seed)
        params = {"objective": "binary"}
    params.update({"num_leaves": 15, "min_data_in_leaf": 5,
                   "verbosity": -1})
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=rounds)
    return bst, X


def _train_categorical(seed=7):
    r = np.random.RandomState(seed)
    X = r.randn(400, 5)
    X[:, 2] = r.randint(0, 12, 400)
    X[r.rand(400) < 0.15, 0] = np.nan
    y = ((X[:, 2] % 3 == 0) + 0.1 * np.nan_to_num(X[:, 0])) \
        .astype(np.float32)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    lgb.Dataset(X, label=y, categorical_feature=[2]),
                    num_boost_round=6)
    Xq = X[:60].copy()
    Xq[0, 2] = 99          # unseen category -> right child
    Xq[1, 2] = np.nan      # NaN category -> right child
    Xq[2, 0] = np.nan      # missing numeric on a NaN-typed feature
    return bst, Xq


# ---------------------------------------------------------------------------
# bit-identity across the objective matrix


def test_pack_bit_identical_to_solo_device_and_close_to_host():
    """One pack holding regression + binary + multiclass + a
    categorical/NaN model answers every member bit-identically to that
    member's SOLO device predict (identical f32 accumulation order),
    and within f32-vs-f64 tolerance of host predict."""
    reg, Xr = _train("regression", seed=0)
    binm, Xb = _train("binary", seed=1)
    mc, Xm = _train("multiclass", seed=2)
    cat, Xq = _train_categorical()
    members = [("reg", reg), ("bin", binm), ("mc", mc), ("cat", cat)]
    queries = {"reg": Xr[:37], "bin": Xb[:64], "mc": Xm[:21],
               "cat": Xq}

    solo = {}
    with Server(min_bucket=4, max_bucket=64) as srv:
        for nm, bst in members:
            srv.load_model(nm, booster=bst)
            solo[nm] = srv.predict(nm, queries[nm], raw_score=True)

    with Server(min_bucket=4, max_bucket=64, pack_size=8) as srv:
        srv.load_pack("matrix", members)
        for nm, bst in members:
            got = srv.predict(nm, queries[nm], raw_score=True)
            assert np.array_equal(got, solo[nm]), \
                f"packed '{nm}' diverged from its solo device predict"
            np.testing.assert_allclose(
                got, bst.predict(queries[nm], raw_score=True),
                rtol=RTOL, atol=ATOL)
            # transformed output rides the member's own converter
            np.testing.assert_allclose(
                srv.predict(nm, queries[nm]), bst.predict(queries[nm]),
                rtol=RTOL, atol=ATOL)


def test_pack_dyadic_bit_identical_to_host():
    """Dyadic members make f32 device sums == f64 host sums, so packed
    serving must match host predict to the last bit."""
    members = [(f"d{i}", dyadic_booster(trees=8 + 6 * i,
                                        seed=30 + i)[0])
               for i in range(3)]
    _, X = dyadic_booster(seed=30)
    with Server(min_bucket=4, max_bucket=64, pack_size=4) as srv:
        srv.load_pack("dy", members)
        for nm, bst in members:
            for rows in (1, 5, 16, 33):
                got = srv.predict(nm, X[:rows], raw_score=True)
                assert np.array_equal(
                    got, bst.predict(X[:rows], raw_score=True))


# ---------------------------------------------------------------------------
# compile accounting


def test_pack_compile_count_bounded():
    """Whatever the member count and traffic mix, one pack compiles
    the fused kernel at most max_compilations(max_bucket) times — the
    bucket ladder bound applies per PACK, not per member."""
    members = [(f"m{i}", dyadic_booster(trees=6 + i, seed=40 + i)[0])
               for i in range(4)]
    _, X = dyadic_booster(seed=40)
    before = _packed_fn()._cache_size()
    with Server(min_bucket=4, max_bucket=64, pack_size=8) as srv:
        srv.load_pack("cc", members)
        rng = np.random.RandomState(0)
        for _ in range(40):
            nm = members[rng.randint(len(members))][0]
            rows = int(rng.randint(1, 100))
            srv.predict(nm, X[:rows], raw_score=True)
        snap = srv.metrics_snapshot()["packs"]["cc"]
    grown = _packed_fn()._cache_size() - before
    bound = max_compilations(64)
    assert grown <= bound, \
        f"fused kernel compiled {grown} times (> ladder bound {bound})"
    assert snap["compile_count"] <= bound
    assert snap["fused_dispatches"] >= 40


# ---------------------------------------------------------------------------
# continuous-batching scheduler


def _mk_req(rows, tag):
    return np.full((rows, 2), tag, np.int32)


def test_slo_scheduler_skip_and_fill_interleaves():
    """A queued request that doesn't fit the forming batch is skipped
    and later, smaller requests backfill around it; fifo stays a
    strict prefix and never interleaves."""
    dispatched = []

    def run(bins):
        dispatched.append(sorted(set(int(v) for v in bins[:, 0])))
        return np.zeros((len(bins), 1), np.float32)

    mb = MicroBatcher(run, max_batch_size=8, max_wait_ms=5.0,
                      scheduler="slo")
    try:
        mb.pause()
        now = time.monotonic()
        f1 = mb.submit(_mk_req(4, 1), deadline=now + 10.0)  # loose
        f2 = mb.submit(_mk_req(6, 2), deadline=now + 2.0)   # tight
        f3 = mb.submit(_mk_req(2, 3), deadline=now + 10.0)  # loose, small
        mb.resume()
        for f in (f1, f2, f3):
            f.result(timeout=10.0)
    finally:
        mb.close()
    # tightest budget first; the 4-row loose request can't join its
    # batch (6+4 > 8) so the 2-row one jumps it
    assert dispatched[0] == [2, 3]
    assert dispatched[1] == [1]
    assert mb.interleave_count == 1


def test_fifo_scheduler_is_a_strict_prefix():
    dispatched = []

    def run(bins):
        dispatched.append(sorted(set(int(v) for v in bins[:, 0])))
        return np.zeros((len(bins), 1), np.float32)

    mb = MicroBatcher(run, max_batch_size=8, max_wait_ms=5.0,
                      scheduler="fifo")
    try:
        mb.pause()
        now = time.monotonic()
        futs = [mb.submit(_mk_req(4, 1), deadline=now + 10.0),
                mb.submit(_mk_req(6, 2), deadline=now + 2.0),
                mb.submit(_mk_req(2, 3), deadline=now + 10.0)]
        mb.resume()
        for f in futs:
            f.result(timeout=10.0)
    finally:
        mb.close()
    # arrival order, batch cut where the next request stops fitting
    assert dispatched[0] == [1]
    assert dispatched[1] == [2, 3]
    assert mb.interleave_count == 0


# ---------------------------------------------------------------------------
# rows-aware admission (the EMA regression that motivated _ServiceModel)


def test_service_model_is_rows_aware():
    """Alternating 1024-row/1s and 8-row/10ms observations must NOT
    collapse into one scalar mean: the fitted linear model projects
    small dispatches cheap and large ones expensive."""
    svc = _ServiceModel(seed_s=0.002)
    for _ in range(30):
        svc.update(1024, 1.0)
        svc.update(8, 0.01)
    assert svc.projected(8) < 0.1, \
        "small-batch projection inherited the large-batch wall"
    assert svc.projected(1024) > 0.5
    # a scalar EMA would sit near the midpoint for both
    assert svc.projected(1024) > 5 * svc.projected(8)


def test_poisoned_estimate_cannot_death_spiral():
    """A cold-start compile poisons the service estimate; since sheds
    never dispatch (and so never refresh it), an empty queue must
    always admit — otherwise the model starves of the samples that
    would correct it. A non-empty queue still projects honestly."""
    fake = [100.0]

    def clock():
        return fake[0]

    def run(bins):
        return np.zeros((len(bins), 1), np.float32)

    mb = MicroBatcher(run, max_batch_size=64, max_wait_ms=2.0,
                      scheduler="slo", clock=clock)
    try:
        mb.pause()
        mb._svc.update(64, 10.0)   # 10s "compile" observation
        # empty queue + 5ms budget: admits despite the 10s estimate
        mb.submit(_mk_req(4, 1), deadline=fake[0] + 0.005)
        assert mb.deadline_shed_count == 0
        # queue now non-empty: the same tight budget projects through
        # the poisoned model and sheds at admission
        with pytest.raises(DeadlineExceeded):
            mb.submit(_mk_req(4, 2), deadline=fake[0] + 0.005)
        assert mb.deadline_shed_count == 1
    finally:
        mb.close(drain_queued=False)


def test_admission_ignores_looser_deadline_queue_rows():
    """slo-mode admission only counts queued rows whose deadline is at
    least as tight as the incoming request — work scheduled BEHIND it
    cannot delay it, so it must not shed it either."""
    fake = [100.0]

    def run(bins):
        return np.zeros((len(bins), 1), np.float32)

    mb = MicroBatcher(run, max_batch_size=64, max_wait_ms=2.0,
                      scheduler="slo", clock=lambda: fake[0])
    try:
        mb.pause()
        mb._svc.update(64, 10.0)
        mb.submit(_mk_req(32, 1))                       # deadline-free
        mb.submit(_mk_req(32, 2), deadline=fake[0] + 60.0)  # loose
        # both queued rows sort behind a tight arrival: admits
        mb.submit(_mk_req(4, 3), deadline=fake[0] + 0.005)
        assert mb.deadline_shed_count == 0
    finally:
        mb.close(drain_queued=False)


# ---------------------------------------------------------------------------
# pack lifecycle: evict drains queued futures to host, exactly once


def test_pack_member_evict_drains_queued_to_host_exactly_once():
    members = [(f"m{i}", dyadic_booster(trees=8, seed=50 + i)[0])
               for i in range(3)]
    boosters = dict(members)
    _, X = dyadic_booster(seed=50)
    with Server(min_bucket=4, max_bucket=64, pack_size=4) as srv:
        srv.load_pack("lp", members)
        for nm, _ in members:
            srv.predict(nm, X[:8], raw_score=True)   # warm
        ents = {nm: srv.registry.get(nm) for nm, _ in members}
        base_reqs = {nm: ents[nm].metrics.snapshot()["requests"]
                     for nm in ents}
        srv.batcher("m0").pause()
        f_evicted = srv.predict_async("m0", X[:5], raw_score=True)
        f_survivor = srv.predict_async("m1", X[:7], raw_score=True)
        assert srv.batcher("m0").queue_depth() == 2
        srv.evict_model("m0")
        # both queued futures resolve through host predict of the
        # entry captured at submit — bit-equal (dyadic), exactly once
        assert np.array_equal(f_evicted.result(timeout=10.0),
                              boosters["m0"].predict(X[:5],
                                                     raw_score=True))
        assert np.array_equal(f_survivor.result(timeout=10.0),
                              boosters["m1"].predict(X[:7],
                                                     raw_score=True))
        for nm, extra in (("m0", 1), ("m1", 1)):
            s = ents[nm].metrics.snapshot()
            assert s["requests"] == base_reqs[nm] + extra
            assert s["fallback_count"] == 1
        # the pack rebuilt for the survivors and stays on the fused
        # path: new version, m0 gone, fused dispatches still growing
        snap = srv.metrics_snapshot()
        psnap = snap["packs"]["lp"]
        assert psnap["version"] == 2
        assert "m0" not in psnap["members"]
        assert psnap["rebuild_drains"] == 2
        assert snap["engine"]["pack_rebuilds"] == 1
        before = psnap["fused_dispatches"]
        got = srv.predict("m1", X[:9], raw_score=True)
        assert np.array_equal(
            got, boosters["m1"].predict(X[:9], raw_score=True))
        assert srv.metrics_snapshot()["packs"]["lp"][
            "fused_dispatches"] > before
        assert "m0" not in srv.registry


# ---------------------------------------------------------------------------
# fault site + chaos under load


@pytest.mark.faults
def test_pack_fault_site_retries_inside_replica_bracket():
    """`serving_pack_predict` fires inside the replica retry bracket:
    one injected fault is retried transparently and the answer stays
    bit-identical."""
    members = [(f"m{i}", dyadic_booster(trees=8, seed=60 + i)[0])
               for i in range(2)]
    boosters = dict(members)
    _, X = dyadic_booster(seed=60)
    faults.clear("serving_pack_predict")
    with Server(min_bucket=4, max_bucket=64, pack_size=4,
                retry_attempts=2, retry_backoff_ms=1.0) as srv:
        srv.load_pack("ft", members)
        srv.predict("m0", X[:8], raw_score=True)     # warm
        with faults.injected("serving_pack_predict", fail=1):
            got = srv.predict("m1", X[:12], raw_score=True)
        assert np.array_equal(
            got, boosters["m1"].predict(X[:12], raw_score=True))
        assert faults.trips("serving_pack_predict") == 1
        assert srv.metrics_snapshot()["packs"]["ft"][
            "device_retries"] >= 1


@pytest.mark.serve_chaos
def test_pack_chaos_swap_and_faults_under_load():
    """Open-loop load over every pack member while `serving_pack_predict`
    faults fire and one member is hot-swapped: zero drops, every answer
    bit-equal to SOME published version of its model."""
    members = [(f"m{i}", dyadic_booster(trees=8 + 4 * i,
                                        seed=70 + i)[0])
               for i in range(3)]
    boosters = dict(members)
    swapped_v2, _ = dyadic_booster(trees=10, seed=99)
    _, X = dyadic_booster(seed=70)
    names = [nm for nm, _ in members]
    faults.clear("serving_pack_predict")

    with Server(min_bucket=4, max_bucket=128, max_wait_ms=1.0,
                max_queue=1024, n_replicas=2, retry_attempts=2,
                retry_backoff_ms=1.0, pack_size=4) as srv:
        srv.load_pack("cp", members)
        for nm in names:
            for rows in (4, 16, 64):
                srv.predict(nm, X[:rows], raw_score=True)  # warm ladder

        def mid(stage):
            faults.schedule("serving_pack_predict", fail=2)
            srv.hot_swap("m1", booster=swapped_v2)

        res = run_open_loop(srv, names[0], X,
                            stages=[(150, 1.0), (150, 1.0)],
                            max_rows=16, raw_score=True,
                            timeout_s=30.0, seed=5, mid_run=mid,
                            names=names)
        faults.clear("serving_pack_predict")
        snap = srv.metrics_snapshot()

    assert res.dropped == 0, f"outcomes: {res.by_outcome()}"
    # m1 answers may come from either published version; the rest are
    # single-version and checked via the ledger helper
    old_m1 = boosters.pop("m1")
    for rec in [r for r in res.ok_records() if r.model == "m1"]:
        ref_old = old_m1.predict(X[rec.lo:rec.hi], raw_score=True)
        ref_new = swapped_v2.predict(X[rec.lo:rec.hi], raw_score=True)
        got = np.asarray(rec.value)
        assert np.array_equal(got, ref_old) or \
            np.array_equal(got, ref_new), \
            f"request {rec.idx}: m1 answer matches neither version"
    rest = LoadResult(
        records=[r for r in res.records if r.model != "m1"],
        wall_s=res.wall_s)
    assert verify_bit_identical(rest, None, X, boosters=boosters) > 0
    assert snap["packs"]["cp"]["version"] >= 2
    assert snap["engine"]["pack_rebuilds"] >= 1
