"""Forced splits + CEGB on the MXU growth path (VERDICT r3 item 5).

Round 4 closed the MXU exclusions: forced splits and the coupled/split
CEGB penalties now run inside grow_tree_mxu (grower_mxu.py), serial and
data-parallel-sharded, matching the portable grower (grower.py:266-300,
reference serial_tree_learner.cpp:459 ForceSplits +
cost_effective_gradient_boosting.hpp DeltaGain). Only the lazy per-row
penalty stays portable (gated with a warning in gbdt.py).

Interpret mode on CPU — slow tier.
"""

import json

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.learner.grower import CegbParams, grow_tree
from lightgbm_tpu.learner.grower_mxu import grow_tree_mxu
from lightgbm_tpu.learner.split import SplitHyperParams
from lightgbm_tpu.parallel import CommSpec, make_mesh
from lightgbm_tpu.parallel.learner import make_sharded_grower

from conftest import make_binary


def _setup(n=3000, f=6, max_bin=31):
    X, y = make_binary(n=n, f=f)
    ds = lgb.Dataset(X, label=y)
    ds.params["max_bin"] = max_bin
    b = ds.binned
    grad = jnp.asarray(-(y - y.mean()), jnp.float32)
    hess = jnp.ones(n, jnp.float32)
    cnt = jnp.ones(n, jnp.float32)
    args = (jnp.asarray(b.bins), grad, hess, cnt,
            jnp.ones(b.num_features, jnp.float32),
            jnp.asarray(b.num_bins), jnp.asarray(b.missing_types == 2),
            jnp.asarray(b.is_categorical))
    return args, int(b.num_bins.max()), b


def _forced_spec(b, feature=3, nested=True):
    """Flattened forced-split arrays for feature/threshold specs, built
    the way gbdt._load_forced_splits does (bin of the value threshold)."""
    # spec 0: root forces `feature` at its median bin; children force
    # feature 4 (left) — mirrors test_advanced nested specs
    nb = int(b.num_bins[feature])
    feat = [feature]
    bins_ = [max(0, nb // 2 - 1)]
    left = [-1]
    right = [-1]
    if nested:
        feat.append(4)
        bins_.append(max(0, int(b.num_bins[4]) // 2 - 1))
        left += [-1]
        right += [-1]
        left[0] = 1
    return (jnp.asarray(feat, jnp.int32), jnp.asarray(bins_, jnp.int32),
            jnp.asarray(left, jnp.int32), jnp.asarray(right, jnp.int32))


def _assert_same_tree(t_a, t_b, rn_a=None, rn_b=None):
    nn = int(t_a.num_nodes)
    assert int(t_b.num_nodes) == nn
    for fld in ("split_feature", "threshold_bin", "left", "right"):
        np.testing.assert_array_equal(
            np.asarray(getattr(t_a, fld))[:nn],
            np.asarray(getattr(t_b, fld))[:nn], err_msg=fld)
    np.testing.assert_allclose(np.asarray(t_a.leaf_value)[:nn],
                               np.asarray(t_b.leaf_value)[:nn],
                               rtol=1e-4, atol=1e-5)
    if rn_a is not None:
        np.testing.assert_array_equal(np.asarray(rn_a), np.asarray(rn_b))


class TestForcedMXUGrower:
    @pytest.mark.parametrize("nested", [False, True])
    def test_matches_portable(self, nested):
        args, bmax, b = _setup()
        forced = _forced_spec(b, nested=nested)
        kw = dict(num_leaves=15, max_depth=-1, hp=SplitHyperParams(),
                  bmax=bmax, forced=forced)
        t_p, rn_p = grow_tree(*args, leafwise=False, **kw)
        t_m, rn_m = grow_tree_mxu(*args, interpret=True, **kw)
        _assert_same_tree(t_p, t_m, rn_p, rn_m)
        assert int(t_m.split_feature[0]) == 3  # root was forced

    def test_forced_survives_overshoot_prune(self):
        # overgrow-and-prune must KEEP forced splits even when their
        # gain would lose the best-first replay
        args, bmax, b = _setup()
        forced = _forced_spec(b, feature=5, nested=False)
        t_m, _ = grow_tree_mxu(*args, num_leaves=8, max_depth=-1,
                               hp=SplitHyperParams(), bmax=bmax,
                               forced=forced, overshoot=2.0,
                               interpret=True)
        assert int(t_m.split_feature[0]) == 5

    def test_sharded_mxu_matches_serial_mxu(self):
        args, bmax, b = _setup(n=4096)
        forced = _forced_spec(b, nested=True)
        kw = dict(num_leaves=15, max_depth=-1, hp=SplitHyperParams(),
                  bmax=bmax)
        t_s, rn_s = grow_tree_mxu(*args, interpret=True, forced=forced,
                                  overshoot=2.0, **kw)
        ndev = 4
        mesh = make_mesh(ndev)
        comm = CommSpec(axis="data", mode="data", num_devices=ndev)
        grower = make_sharded_grower(
            mesh, comm, leafwise=False, use_mxu=True, interpret=True,
            forced=forced, mxu_kwargs=dict(overshoot=2.0), **kw)
        with mesh:
            t_p, rn_p = grower(*args)
        _assert_same_tree(t_s, t_p, rn_s, rn_p)


class TestForcedWithEfbMXU:
    def test_forced_split_bundled_matches_portable(self):
        # forced stats under SEGMENTED EFB come from a per-slot
        # bundle-space expansion gather (grower_mxu one_pass) — compare
        # against the portable grower's expansion-based forced path
        from lightgbm_tpu.efb import (build_plan, bundle_matrix,
                                      make_device_tables)
        rng = np.random.RandomState(3)
        n, f = 4000, 24
        X = np.zeros((n, f))
        for g0 in range(0, f, 8):
            which = rng.randint(g0, g0 + 8, size=n)
            X[np.arange(n), which] = rng.rand(n) + 0.5
        y = (X[:, 0] + X[:, 8] > 0.8).astype(np.float32)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 15}).binned
        plan = build_plan(np.asarray(ds.bins), ds.num_bins,
                          ds.default_bins,
                          np.asarray(ds.is_categorical),
                          max_bundle_bins=256)
        assert plan is not None and plan.effective
        efb = make_device_tables(
            plan, ds.default_bins, num_bins=ds.num_bins,
            missing_is_nan=(ds.missing_types == 2),
            is_cat=np.asarray(ds.is_categorical))
        assert efb.scan is not None
        bund = jnp.asarray(bundle_matrix(np.asarray(ds.bins), plan))
        p = np.full(n, 0.5, np.float32)
        g = jnp.asarray(p - y)
        h = jnp.asarray(p * (1 - p))
        cnt = jnp.ones(n, jnp.float32)
        args = (bund, g, h, cnt, jnp.ones(f, jnp.float32),
                jnp.asarray(ds.num_bins),
                jnp.asarray(ds.missing_types == 2),
                jnp.asarray(ds.is_categorical))
        # force feature 5 (a bundled sparse feature) at its median bin
        nb5 = int(ds.num_bins[5])
        forced = (jnp.asarray([5], jnp.int32),
                  jnp.asarray([max(0, nb5 // 2 - 1)], jnp.int32),
                  jnp.asarray([-1], jnp.int32),
                  jnp.asarray([-1], jnp.int32))
        kw = dict(num_leaves=15, max_depth=0,
                  hp=SplitHyperParams(min_data_in_leaf=20),
                  bmax=int(ds.num_bins.max()), forced=forced, efb=efb)
        t_p, rn_p = grow_tree(*args, leafwise=False, **kw)
        t_m, rn_m = grow_tree_mxu(*args, interpret=True, **kw)
        _assert_same_tree(t_p, t_m, rn_p, rn_m)
        assert int(t_m.split_feature[0]) == 5


class TestCegbMXUGrower:
    def _cegb(self, f, coupled_pen):
        cfg = CegbParams(tradeoff=1.0, penalty_split=0.01,
                         has_coupled=True, has_lazy=False)
        state = (jnp.asarray(coupled_pen, jnp.float32),
                 jnp.zeros(f, jnp.float32), jnp.zeros(f, bool),
                 jnp.zeros((1, 1), bool))
        return cfg, state

    def test_matches_portable(self):
        args, bmax, b = _setup()
        cfg, state = self._cegb(b.num_features,
                                [0.0, 1e6, 0.0, 0.0, 0.0, 0.0])
        kw = dict(num_leaves=15, max_depth=-1, hp=SplitHyperParams(),
                  bmax=bmax, cegb_cfg=cfg, cegb_state=state)
        t_p, rn_p, (fu_p, _) = grow_tree(*args, leafwise=False, **kw)
        t_m, rn_m, (fu_m, _) = grow_tree_mxu(*args, interpret=True, **kw)
        _assert_same_tree(t_p, t_m, rn_p, rn_m)
        np.testing.assert_array_equal(np.asarray(fu_p), np.asarray(fu_m))
        # the huge coupled penalty keeps feature 1 out of the tree
        nn = int(t_m.num_nodes)
        assert not np.any(np.asarray(t_m.split_feature[:nn]) == 1)

    def test_sharded_mxu_matches_serial_mxu(self):
        args, bmax, b = _setup(n=4096)
        cfg, state = self._cegb(b.num_features, [0.5] * 6)
        kw = dict(num_leaves=15, max_depth=-1, hp=SplitHyperParams(),
                  bmax=bmax)
        t_s, rn_s, (fu_s, _) = grow_tree_mxu(
            *args, interpret=True, cegb_cfg=cfg, cegb_state=state,
            overshoot=2.0, **kw)
        ndev = 4
        mesh = make_mesh(ndev)
        comm = CommSpec(axis="data", mode="data", num_devices=ndev)
        grower = make_sharded_grower(
            mesh, comm, leafwise=False, use_mxu=True, interpret=True,
            cegb_cfg=cfg, with_cegb_state=True,
            mxu_kwargs=dict(overshoot=2.0), **kw)
        with mesh:
            t_p, rn_p, (fu_p, _) = grower(*args, state)
        _assert_same_tree(t_s, t_p, rn_s, rn_p)
        np.testing.assert_array_equal(np.asarray(fu_s), np.asarray(fu_p))


class TestBoosterLevelMXU:
    """End-to-end: booster on the (interpret) MXU path honors forced
    splits and CEGB semantics (mirrors test_advanced on scatter)."""

    def _train_mxu(self, params, X, y, rounds):
        bst = lgb.Booster(params=params,
                          train_set=lgb.Dataset(X, label=y))
        g = bst.gbdt
        g._hist_impl = "mxu"
        g._mxu_interpret = True
        for _ in range(rounds):
            bst.update()
        return bst

    def test_forced_root(self, tmp_path):
        r = np.random.RandomState(0)
        X = r.randn(2000, 5).astype(np.float32)
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
        fn = tmp_path / "forced.json"
        fn.write_text(json.dumps({"feature": 2, "threshold": 0.0}))
        bst = self._train_mxu(
            {"objective": "binary", "num_leaves": 8, "verbosity": -1,
             "forcedsplits_filename": str(fn), "min_data_in_leaf": 5},
            X, y, 3)
        for t in bst.dump_model()["tree_info"]:
            assert t["tree_structure"]["split_feature"] == 2

    def test_cegb_coupled_blocks_feature(self):
        r = np.random.RandomState(1)
        X = r.randn(3000, 6).astype(np.float32)
        y = (X[:, 0] + 0.5 * X[:, 1] +
             0.1 * r.randn(3000) > 0).astype(np.float32)
        bst = self._train_mxu(
            {"objective": "binary", "num_leaves": 16, "verbosity": -1,
             "cegb_tradeoff": 1.0,
             "cegb_penalty_feature_coupled":
                 [0.0, 1e6, 0.0, 0.0, 0.0, 0.0]},
            X, y, 5)
        assert bst.feature_importance()[1] == 0
