"""MXU kernel-path tests (interpret mode, runs on CPU).

grower_mxu/histogram_mxu are the TPU fast path; Pallas interpret mode
executes the same kernel logic on CPU so the suite can check it without
hardware. Equality target: grower.grow_tree with identical inputs.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # Pallas interpret mode: minutes per test

import jax
import jax.numpy as jnp

from lightgbm_tpu.data import BinnedDataset, Metadata
from lightgbm_tpu.learner.grower import grow_tree
from lightgbm_tpu.learner.grower_mxu import grow_tree_mxu
from lightgbm_tpu.learner.histogram import build_histograms
from lightgbm_tpu.learner.histogram_mxu import (build_histograms_mxu,
                                                node_values_mxu)
from lightgbm_tpu.learner.split import SplitHyperParams


def _data(n=4000, f=6, seed=0, with_nan=False, with_cat=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    if with_cat:
        X[:, 2] = rng.randint(0, 12, size=n)
    if with_nan:
        X[rng.rand(n) < 0.05, 1] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.5 * np.nan_to_num(X[:, 1]) > 0) \
        .astype(np.float32)
    ds = BinnedDataset.from_raw(
        X, Metadata(n, label=y), max_bin=63,
        categorical_features=[2] if with_cat else None)
    p = np.full(n, 0.5, np.float32)
    return ds, jnp.asarray(p - y), jnp.asarray(p * (1 - p))


def _mxu_args(ds, g, h):
    """Positional args of grow_tree_mxu for a dataset + grad/hess."""
    return (jnp.asarray(ds.bins), g, h, jnp.ones(ds.num_data, jnp.float32),
            jnp.ones(ds.num_features, jnp.float32),
            jnp.asarray(ds.num_bins), jnp.asarray(ds.missing_types == 2),
            jnp.asarray(ds.is_categorical))


def _grow_both(ds, grad, hess, num_leaves=15, **extra):
    bins = jnp.asarray(ds.bins)
    cnt = jnp.ones(ds.num_data, jnp.float32)
    args = (bins, grad, hess, cnt,
            jnp.ones(ds.num_features, jnp.float32),
            jnp.asarray(ds.num_bins), jnp.asarray(ds.missing_types == 2),
            jnp.asarray(ds.is_categorical))
    kw = dict(num_leaves=num_leaves, max_depth=0,
              hp=SplitHyperParams(min_data_in_leaf=20),
              bmax=int(ds.num_bins.max()), **extra)
    t_ref, r_ref = grow_tree(*args, leafwise=False, **kw)
    t_mxu, r_mxu = grow_tree_mxu(*args, interpret=True, **kw)
    return t_ref, r_ref, t_mxu, r_mxu


def _assert_same_tree(t_ref, r_ref, t_mxu, r_mxu):
    assert int(t_ref.num_leaves) == int(t_mxu.num_leaves)
    nn = int(t_ref.num_nodes)
    for fld in ("split_feature", "threshold_bin", "left", "right",
                "is_cat", "default_left"):
        np.testing.assert_array_equal(
            np.asarray(getattr(t_ref, fld))[:nn],
            np.asarray(getattr(t_mxu, fld))[:nn], err_msg=fld)
    np.testing.assert_allclose(np.asarray(t_ref.leaf_value)[:nn],
                               np.asarray(t_mxu.leaf_value)[:nn],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(r_ref), np.asarray(r_mxu))


class TestMXUGrower:
    def test_matches_reference_grower(self):
        ds, g, h = _data()
        _assert_same_tree(*_grow_both(ds, g, h))

    def test_matches_with_nan(self):
        ds, g, h = _data(with_nan=True, seed=1)
        _assert_same_tree(*_grow_both(ds, g, h))

    def test_matches_with_categorical(self):
        ds, g, h = _data(with_cat=True, seed=2)
        _assert_same_tree(*_grow_both(ds, g, h))

    def test_histogram_matches_scatter(self):
        ds, g, h = _data(n=3000)
        bins = jnp.asarray(ds.bins)
        cnt = jnp.ones(ds.num_data, jnp.float32)
        slot = jnp.asarray(
            np.random.RandomState(0).randint(-1, 8, size=ds.num_data)
            .astype(np.int32))
        bmax = int(ds.num_bins.max())
        hm = build_histograms_mxu(bins, g, h, cnt, slot, num_slots=8,
                                  bmax=bmax, interpret=True)
        hr = build_histograms(bins, g, h, slot, cnt, num_slots=8, bmax=bmax)
        np.testing.assert_allclose(np.asarray(hm), np.asarray(hr)[:8],
                                   rtol=1e-4, atol=1e-4)

    def test_histogram_single_precision_close(self):
        # gpu_use_dp=false mode: grad sums stay hi/lo-exact, hessian sums
        # ride single bf16 (~2^-9 relative)
        ds, g, h = _data(n=3000)
        bins = jnp.asarray(ds.bins)
        cnt = jnp.ones(ds.num_data, jnp.float32)
        slot = jnp.asarray(
            np.random.RandomState(1).randint(0, 8, size=ds.num_data)
            .astype(np.int32))
        bmax = int(ds.num_bins.max())
        hm = build_histograms_mxu(bins, g, h, cnt, slot, num_slots=8,
                                  bmax=bmax, double_prec=False,
                                  interpret=True)
        hr = build_histograms(bins, g, h, slot, cnt, num_slots=8, bmax=bmax)
        np.testing.assert_allclose(np.asarray(hm[..., 0]),
                                   np.asarray(hr)[:8, ..., 0],
                                   rtol=1e-4, atol=1e-4)  # grads hi/lo
        np.testing.assert_allclose(np.asarray(hm[..., 1]),
                                   np.asarray(hr)[:8, ..., 1],
                                   rtol=2e-2, atol=1e-2)  # hess bf16
        np.testing.assert_array_equal(np.asarray(hm[..., 2]),
                                      np.asarray(hr)[:8, ..., 2])

    def test_node_values_lookup(self):
        rng = np.random.RandomState(0)
        node = jnp.asarray(rng.randint(0, 61, size=5000).astype(np.int32))
        vals = np.full(62, np.nan, np.float32)
        vals[:61] = rng.randn(61)
        vals_d = jnp.asarray(vals)
        got = node_values_mxu(node, vals_d, interpret=True)
        np.testing.assert_allclose(np.asarray(got),
                                   vals[np.asarray(node)], rtol=1e-5)

    def test_large_node_ids_route_exactly(self):
        # child/node ids beyond 256 exercise the base-256 table packing
        ds, g, h = _data(n=20000, f=8, seed=3)
        t_ref, r_ref, t_mxu, r_mxu = _grow_both(ds, g, h, num_leaves=255)
        _assert_same_tree(t_ref, r_ref, t_mxu, r_mxu)

    @pytest.mark.parametrize("tail_cap", [0, 2, 4])
    def test_subtraction_matches_full_build(self, tail_cap):
        # the sibling-subtraction path (smaller child built, larger =
        # parent - smaller, stale parents 2 slots) must grow the same
        # tree as building every child's histogram from rows
        ds, g, h = _data(n=6000, f=8, seed=4, with_nan=True)
        args = _mxu_args(ds, g, h)
        kw = dict(num_leaves=31, max_depth=0,
                  hp=SplitHyperParams(min_data_in_leaf=20),
                  bmax=int(ds.num_bins.max()), interpret=True,
                  tail_split_cap=tail_cap)
        t0, r0 = grow_tree_mxu(*args, hist_subtraction=False, **kw)
        t1, r1 = grow_tree_mxu(*args, hist_subtraction=True, **kw)
        _assert_same_tree(t0, r0, t1, r1)

    @pytest.mark.parametrize("overshoot", [2.0, 3.0])
    def test_overshoot_prune_matches_leafwise(self, overshoot):
        # overgrow-and-prune replays the exact best-first order over the
        # recorded gains; with ample overshoot the per-row leaf outputs
        # must match the strict leaf-wise scatter grower up to kernel
        # precision, and the pruned tree must be self-consistent
        from lightgbm_tpu.learner.predict import predict_binned_tree
        ds, g, h = _data(n=6000, f=8, seed=6, with_nan=True)
        args = _mxu_args(ds, g, h)
        kw = dict(num_leaves=31, max_depth=0,
                  hp=SplitHyperParams(min_data_in_leaf=20),
                  bmax=int(ds.num_bins.max()))
        t_lw, r_lw = grow_tree(*args, leafwise=True, **kw)
        t_ov, r_ov = grow_tree_mxu(*args, interpret=True,
                                   overshoot=overshoot, **kw)
        assert int(t_ov.num_leaves) == 31
        # row_node agrees with routing fresh rows through the pruned tree
        vals_route = predict_binned_tree(
            t_ov, args[0], jnp.asarray(ds.num_bins),
            jnp.asarray(ds.missing_types == 2))
        vals_rows = np.asarray(t_ov.leaf_value)[np.asarray(r_ov)]
        np.testing.assert_allclose(np.asarray(vals_route), vals_rows,
                                   rtol=1e-5, atol=1e-6)
        # per-row outputs match strict leaf-wise growth (kernel-precision
        # tie-breaks allowed at overshoot=2 where coverage can clip)
        v_lw = np.asarray(t_lw.leaf_value)[np.asarray(r_lw)]
        if overshoot >= 3.0:
            mismatch = np.mean(np.abs(v_lw - vals_rows) > 1e-2)
            assert mismatch < 0.02, f"row mismatch rate {mismatch}"

    def test_overshoot_bridge_gate_valid_tree(self):
        # growth_bridge_gate skips the bridge/fixups for near-complete
        # trees; the pruned tree must still reach the leaf budget and
        # stay self-consistent (the gate only trims overshoot COVERAGE,
        # never the final structure invariants)
        from lightgbm_tpu.learner.predict import predict_binned_tree
        ds, g, h = _data(n=6000, f=8, seed=9, with_nan=True)
        args = _mxu_args(ds, g, h)
        t, r = grow_tree_mxu(
            *args, num_leaves=31, max_depth=0,
            hp=SplitHyperParams(min_data_in_leaf=20),
            bmax=int(ds.num_bins.max()), interpret=True, overshoot=2.0,
            bridge_gate=0.93)
        assert int(t.num_leaves) == 31
        vals_route = predict_binned_tree(
            t, args[0], jnp.asarray(ds.num_bins),
            jnp.asarray(ds.missing_types == 2))
        vals_rows = np.asarray(t.leaf_value)[np.asarray(r)]
        np.testing.assert_allclose(np.asarray(vals_route), vals_rows,
                                   rtol=1e-5, atol=1e-6)

    def test_overshoot_respects_max_depth(self):
        # overgrow-and-prune must not let the overshoot expansion smuggle
        # in nodes deeper than max_depth
        ds, g, h = _data(n=6000, f=8, seed=7)
        args = _mxu_args(ds, g, h)
        t, _ = grow_tree_mxu(
            *args, num_leaves=31, max_depth=3,
            hp=SplitHyperParams(min_data_in_leaf=20),
            bmax=int(ds.num_bins.max()), interpret=True, overshoot=2.0)
        nn = int(t.num_nodes)
        # this dataset fills the full depth-3 tree; == 8 also catches an
        # under-grown stub, not just an over-deep one
        assert int(t.num_leaves) == 8
        assert int(np.asarray(t.depth)[:nn].max()) <= 3

    def test_hybrid_tail_reaches_num_leaves(self):
        # the throttled tail must still fill the leaf budget
        ds, g, h = _data(n=6000, f=8, seed=5)
        args = _mxu_args(ds, g, h)
        t, _ = grow_tree_mxu(
            *args, num_leaves=31, max_depth=0,
            hp=SplitHyperParams(min_data_in_leaf=20),
            bmax=int(ds.num_bins.max()), interpret=True, tail_split_cap=2)
        assert int(t.num_leaves) == 31


class TestQuantizedGrad:
    """use_quantized_grad: 3-channel integer histograms + exact leaf refit
    (split search may differ from exact histograms on near-tie gains; the
    fitted leaf values must not)."""

    def test_quantized_histogram_integer_sums(self):
        from lightgbm_tpu.learner.histogram_mxu import (
            build_histograms_mxu_v2, quantize_gradients)
        ds, g, h = _data(n=3000)
        bins = jnp.asarray(ds.bins)
        cnt = jnp.ones(ds.num_data, jnp.float32)
        slot = jnp.asarray(
            np.random.RandomState(3).randint(-1, 8, size=ds.num_data)
            .astype(np.int32))
        bmax = int(ds.num_bins.max())
        gq, hq, gs, hs = quantize_gradients(g, h, jax.random.PRNGKey(0))
        hm = build_histograms_mxu_v2(bins, gq, hq, cnt, slot, num_slots=8,
                                     bmax=bmax, quantized=True,
                                     interpret=True)
        # per-slot integer sums must match an exact host scatter of gq/hq
        gq_h = np.asarray(gq)
        hq_h = np.asarray(hq)
        sl = np.asarray(slot)
        bn = np.asarray(ds.bins)
        want = np.zeros((8, ds.num_features, bmax, 3))
        for r in range(ds.num_data):
            if sl[r] < 0:
                continue
            for f in range(ds.num_features):
                want[sl[r], f, bn[r, f], 0] += gq_h[r]
                want[sl[r], f, bn[r, f], 1] += hq_h[r]
                want[sl[r], f, bn[r, f], 2] += 1
        np.testing.assert_allclose(np.asarray(hm), want, atol=1e-3)

    def test_quantization_unbiased_and_in_range(self):
        from lightgbm_tpu.learner.histogram_mxu import quantize_gradients
        rng = np.random.RandomState(0)
        g = jnp.asarray(rng.randn(20000).astype(np.float32))
        h = jnp.asarray(rng.rand(20000).astype(np.float32))
        gq, hq, gs, hs = quantize_gradients(g, h, jax.random.PRNGKey(1))
        gq_h, hq_h = np.asarray(gq), np.asarray(hq)
        assert np.all(gq_h == np.round(gq_h))
        assert gq_h.min() >= -127 and gq_h.max() <= 127
        assert hq_h.min() >= 0 and hq_h.max() <= 127
        # unbiased: mean reconstruction error ~0 vs per-element scale
        err = gq_h * float(gs) - np.asarray(g)
        assert abs(err.mean()) < float(gs) * 0.02

    def test_node_sums_exact(self):
        from lightgbm_tpu.learner.histogram_mxu import node_sums_mxu
        ds, g, h = _data(n=5000)
        cnt = jnp.ones(ds.num_data, jnp.float32)
        node = jnp.asarray(
            np.random.RandomState(4).randint(0, 29, size=ds.num_data)
            .astype(np.int32))
        got = np.asarray(node_sums_mxu(node, g, h, cnt, num_nodes=29,
                                       interpret=True))
        nh = np.asarray(node)
        gh, hh = np.asarray(g, np.float64), np.asarray(h, np.float64)
        for j in range(29):
            m = nh == j
            np.testing.assert_allclose(got[j, 0], gh[m].sum(), rtol=2e-5,
                                       atol=1e-5)
            np.testing.assert_allclose(got[j, 1], hh[m].sum(), rtol=2e-5,
                                       atol=1e-5)
            np.testing.assert_allclose(got[j, 2], m.sum(), rtol=0,
                                       atol=0.01)

    def test_quantized_grower_leaf_values_exact(self):
        # the tree may pick slightly different near-tie splits; whatever
        # tree it grows, leaf values must equal the exact refit over the
        # final row partition (incl. after overgrow-and-prune remapping)
        ds, g, h = _data(n=4000, seed=6)
        args = _mxu_args(ds, g, h)
        hp = SplitHyperParams(min_data_in_leaf=20)
        t, rn = grow_tree_mxu(
            *args, num_leaves=15, max_depth=0, hp=hp,
            bmax=int(ds.num_bins.max()), interpret=True, overshoot=2.0,
            quantized_grad=True, rng_key=jax.random.PRNGKey(2))
        assert int(t.num_leaves) == 15
        rn_h = np.asarray(rn)
        gh = np.asarray(g, np.float64)
        hh = np.asarray(h, np.float64)
        lv = np.asarray(t.leaf_value)
        for j in np.where(np.asarray(t.is_leaf))[0]:
            m = rn_h == j
            if not m.any():
                continue
            want = -gh[m].sum() / (hh[m].sum() + hp.lambda_l2)
            np.testing.assert_allclose(lv[j], want, rtol=1e-3, atol=1e-4)

    def test_quantized_grower_close_to_exact_tree(self):
        # on a well-separated dataset the quantized search picks the same
        # splits as the exact one
        ds, g, h = _data(n=4000, seed=7)
        args = _mxu_args(ds, g, h)
        kw = dict(num_leaves=15, max_depth=0,
                  hp=SplitHyperParams(min_data_in_leaf=20),
                  bmax=int(ds.num_bins.max()), interpret=True)
        t0, _ = grow_tree_mxu(*args, **kw)
        t1, _ = grow_tree_mxu(*args, **kw, quantized_grad=True,
                              rng_key=jax.random.PRNGKey(3))
        nn = int(t0.num_nodes)
        assert int(t1.num_nodes) == nn
        same = (np.asarray(t0.split_feature)[:nn] ==
                np.asarray(t1.split_feature)[:nn]).mean()
        assert same >= 0.9


class TestScanKernel:
    """Fused best-split scan kernel parity vs find_best_splits
    (split_kernel.py; opt-in via grow_tree_mxu(use_scan_kernel=True))."""

    @pytest.mark.parametrize("mono_on,nan_on", [(False, False),
                                                (False, True),
                                                (True, False),
                                                (True, True)])
    def test_matches_xla_scan(self, mono_on, nan_on):
        from lightgbm_tpu.learner.split import find_best_splits
        from lightgbm_tpu.learner.split_kernel import (
            find_best_splits_kernel)
        rng = np.random.RandomState(3)
        S, F, B = 6, 5, 31
        hist = jnp.asarray(np.abs(rng.rand(S, F, B, 3)) *
                           np.array([1.0, 1.0, 50.0]))
        pg = jnp.asarray(np.asarray(hist[..., 0]).sum((1, 2)) / F)
        ph = jnp.asarray(np.asarray(hist[..., 1]).sum((1, 2)) / F)
        pc = jnp.asarray(np.asarray(hist[..., 2]).sum((1, 2)) / F)
        hist = hist / hist.sum(2, keepdims=True) * \
            jnp.stack([pg, ph, pc], -1)[:, None, None, :]
        hp = SplitHyperParams(min_data_in_leaf=3, has_monotone=mono_on)
        kw = dict(monotone=jnp.asarray([1, -1, 0, 0, 0], jnp.int32),
                  cons_min=jnp.full(S, -0.5), cons_max=jnp.full(S, 0.5),
                  depth=jnp.arange(S)) if mono_on else {}
        mnan = jnp.asarray([nan_on] * 2 + [False] * (F - 2))
        args = (hist, pg, ph, pc, jnp.zeros(S), jnp.full(F, B, jnp.int32),
                mnan, jnp.zeros(F, bool), jnp.ones(F, jnp.float32), hp)
        a = find_best_splits(*args, **kw)
        b = find_best_splits_kernel(*args, interpret=True, **kw)
        for fld in ("feature", "threshold_bin", "default_left"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a, fld)), np.asarray(getattr(b, fld)),
                err_msg=fld)
        for fld in ("gain", "left_grad", "left_hess", "left_count",
                    "left_output", "right_output"):
            np.testing.assert_allclose(
                np.asarray(getattr(a, fld)), np.asarray(getattr(b, fld)),
                rtol=2e-5, atol=1e-5, err_msg=fld)

    def test_grower_with_scan_kernel_matches(self):
        ds, g, h = _data(n=3000, seed=9)
        args = _mxu_args(ds, g, h)
        kw = dict(num_leaves=15, max_depth=0,
                  hp=SplitHyperParams(min_data_in_leaf=20),
                  bmax=int(ds.num_bins.max()), interpret=True)
        t0, r0 = grow_tree_mxu(*args, **kw)
        t1, r1 = grow_tree_mxu(*args, **kw, use_scan_kernel=True)
        nn = int(t0.num_nodes)
        assert int(t1.num_nodes) == nn
        np.testing.assert_array_equal(np.asarray(t0.split_feature)[:nn],
                                      np.asarray(t1.split_feature)[:nn])
        np.testing.assert_allclose(np.asarray(t0.leaf_value)[:nn],
                                   np.asarray(t1.leaf_value)[:nn],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(r0), np.asarray(r1))
