"""Fast MXU-path smoke test kept in the DEFAULT suite (the exhaustive
kernel parity matrix lives in test_mxu_kernels.py behind -m slow)."""

import numpy as np

import jax
import jax.numpy as jnp

from lightgbm_tpu.data import BinnedDataset, Metadata
from lightgbm_tpu.learner.grower import grow_tree
from lightgbm_tpu.learner.grower_mxu import grow_tree_mxu
from lightgbm_tpu.learner.split import SplitHyperParams


def test_mxu_grower_matches_portable_small():
    rng = np.random.RandomState(0)
    n = 1200
    X = rng.randn(n, 5).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    ds = BinnedDataset.from_raw(X, Metadata(n, label=y), max_bin=31)
    g = jnp.asarray(0.5 - y)
    h = jnp.full(n, 0.25, jnp.float32)
    cnt = jnp.ones(n, jnp.float32)
    args = (jnp.asarray(ds.bins), g, h, cnt,
            jnp.ones(ds.num_features, jnp.float32),
            jnp.asarray(ds.num_bins), jnp.asarray(ds.missing_types == 2),
            jnp.asarray(ds.is_categorical))
    kw = dict(num_leaves=7, max_depth=0,
              hp=SplitHyperParams(min_data_in_leaf=20),
              bmax=int(ds.num_bins.max()))
    t_ref, r_ref = grow_tree(*args, leafwise=False, **kw)
    t_mxu, r_mxu = grow_tree_mxu(*args, interpret=True, **kw)
    nn = int(t_ref.num_nodes)
    assert int(t_mxu.num_nodes) == nn
    np.testing.assert_array_equal(
        np.asarray(t_ref.split_feature)[:nn],
        np.asarray(t_mxu.split_feature)[:nn])
    np.testing.assert_array_equal(
        np.asarray(t_ref.threshold_bin)[:nn],
        np.asarray(t_mxu.threshold_bin)[:nn])
    np.testing.assert_allclose(np.asarray(t_ref.leaf_value)[:nn],
                               np.asarray(t_mxu.leaf_value)[:nn],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(r_ref), np.asarray(r_mxu))
