"""Native C++ layer tests: forest predictor parity (cext/predict.cpp)
and the Dask wrapper surface (dask.py)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
import lightgbm_tpu.cext as cext


def _toggle_numpy_path(bst):
    """Force the numpy prediction path for comparison."""
    class _Ctx:
        def __enter__(self):
            self._orig = cext.predict_available
            cext.predict_available = lambda: False
            bst._model = None

        def __exit__(self, *a):
            cext.predict_available = self._orig
            bst._model = None
    return _Ctx()


@pytest.mark.skipif(not cext.predict_available(),
                    reason="no native compiler")
class TestNativePredictor:
    def _model(self):
        rng = np.random.RandomState(0)
        X = rng.randn(8000, 8).astype(np.float32)
        X[rng.rand(8000) < 0.05, 2] = np.nan
        X[:, 3] = rng.randint(0, 10, 8000)
        y = (np.nan_to_num(X[:, 0]) + 0.5 * X[:, 1] +
             (X[:, 3] > 5) > 0.5).astype(np.float32)
        bst = lgb.train({"objective": "binary", "num_leaves": 31,
                         "verbosity": -1},
                        lgb.Dataset(X, label=y, categorical_feature=[3]),
                        20)
        return bst, X

    def test_matches_numpy_path(self):
        bst, X = self._model()
        p_native = bst.predict(X)
        with _toggle_numpy_path(bst):
            p_numpy = bst.predict(X)
        np.testing.assert_allclose(p_native, p_numpy, rtol=1e-10)

    def test_leaf_index_matches(self):
        bst, X = self._model()
        l_native = bst.predict(X, pred_leaf=True)
        with _toggle_numpy_path(bst):
            l_numpy = bst.predict(X, pred_leaf=True)
        np.testing.assert_array_equal(l_native, l_numpy)

    def test_multiclass(self):
        rng = np.random.RandomState(1)
        X = rng.randn(5000, 6).astype(np.float32)
        y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)
        bst = lgb.train({"objective": "multiclass", "num_class": 3,
                         "verbosity": -1}, lgb.Dataset(X, label=y), 10)
        p_native = bst.predict(X)
        with _toggle_numpy_path(bst):
            p_numpy = bst.predict(X)
        np.testing.assert_allclose(p_native, p_numpy, rtol=1e-10)

    def test_linear_trees(self):
        rng = np.random.RandomState(2)
        X = rng.randn(4000, 4).astype(np.float32)
        y = np.where(X[:, 0] > 0, 2 * X[:, 1], -X[:, 1]).astype(np.float32)
        bst = lgb.train({"objective": "regression", "num_leaves": 8,
                         "linear_tree": True, "verbosity": -1},
                        lgb.Dataset(X, label=y), 10)
        p_native = bst.predict(X)
        with _toggle_numpy_path(bst):
            p_numpy = bst.predict(X)
        np.testing.assert_allclose(p_native, p_numpy, rtol=1e-8)

    def test_start_num_iteration(self):
        bst, X = self._model()
        p_native = bst.predict(X, start_iteration=5, num_iteration=10)
        with _toggle_numpy_path(bst):
            p_numpy = bst.predict(X, start_iteration=5, num_iteration=10)
        np.testing.assert_allclose(p_native, p_numpy, rtol=1e-10)


class TestDaskSurface:
    def test_estimators_importable(self):
        from lightgbm_tpu.dask import (DaskLGBMClassifier,
                                       DaskLGBMRanker, DaskLGBMRegressor)
        assert DaskLGBMClassifier is not None
        assert DaskLGBMRegressor is not None
        assert DaskLGBMRanker is not None

    def test_raises_without_dask(self):
        from lightgbm_tpu import dask as lgb_dask
        if lgb_dask._DASK_AVAILABLE:
            pytest.skip("dask installed")
        with pytest.raises(ImportError):
            lgb_dask.DaskLGBMClassifier(n_estimators=5)


class TestFileIO:
    """Pluggable file IO (reference VirtualFileReader/Writer,
    file_io.cpp): registered schemes carry model save/load."""

    def test_registered_scheme_round_trip(self):
        import io as _io
        from lightgbm_tpu.utils import file_io

        store = {}

        class MemText(_io.StringIO):
            def __init__(self, path, mode):
                self._p, self._m = path, mode
                super().__init__(store.get(path, "")
                                 if "r" in mode else "")

            def close(self):
                if "w" in self._m:
                    store[self._p] = self.getvalue()
                super().close()

        file_io.register_filesystem("memtest", MemText)
        try:
            r = np.random.RandomState(0)
            X = r.randn(400, 4)
            y = (X[:, 0] > 0).astype(np.float32)
            bst = lgb.train({"objective": "binary", "verbosity": -1},
                            lgb.Dataset(X, label=y), 3)
            bst.save_model("memtest://m.txt")
            assert "memtest://m.txt" in store
            bst2 = lgb.Booster(model_file="memtest://m.txt")
            np.testing.assert_allclose(bst2.predict(X), bst.predict(X),
                                       rtol=1e-7, atol=1e-8)
        finally:
            file_io._SCHEMES.pop("memtest", None)

    def test_unknown_scheme_raises(self):
        from lightgbm_tpu.utils.file_io import open_file
        with pytest.raises(ValueError, match="no filesystem registered"):
            open_file("nosuchscheme://x/y", "r")
