"""Native C++ layer tests: forest predictor parity (cext/predict.cpp)
and the Dask wrapper surface (dask.py)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
import lightgbm_tpu.cext as cext


def _toggle_numpy_path(bst):
    """Force the numpy prediction path for comparison."""
    class _Ctx:
        def __enter__(self):
            self._orig = cext.predict_available
            cext.predict_available = lambda: False
            bst._model = None

        def __exit__(self, *a):
            cext.predict_available = self._orig
            bst._model = None
    return _Ctx()


@pytest.mark.skipif(not cext.predict_available(),
                    reason="no native compiler")
class TestNativePredictor:
    def _model(self):
        rng = np.random.RandomState(0)
        X = rng.randn(8000, 8).astype(np.float32)
        X[rng.rand(8000) < 0.05, 2] = np.nan
        X[:, 3] = rng.randint(0, 10, 8000)
        y = (np.nan_to_num(X[:, 0]) + 0.5 * X[:, 1] +
             (X[:, 3] > 5) > 0.5).astype(np.float32)
        bst = lgb.train({"objective": "binary", "num_leaves": 31,
                         "verbosity": -1},
                        lgb.Dataset(X, label=y, categorical_feature=[3]),
                        20)
        return bst, X

    def test_matches_numpy_path(self):
        bst, X = self._model()
        p_native = bst.predict(X)
        with _toggle_numpy_path(bst):
            p_numpy = bst.predict(X)
        np.testing.assert_allclose(p_native, p_numpy, rtol=1e-10)

    def test_leaf_index_matches(self):
        bst, X = self._model()
        l_native = bst.predict(X, pred_leaf=True)
        with _toggle_numpy_path(bst):
            l_numpy = bst.predict(X, pred_leaf=True)
        np.testing.assert_array_equal(l_native, l_numpy)

    def test_multiclass(self):
        rng = np.random.RandomState(1)
        X = rng.randn(5000, 6).astype(np.float32)
        y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)
        bst = lgb.train({"objective": "multiclass", "num_class": 3,
                         "verbosity": -1}, lgb.Dataset(X, label=y), 10)
        p_native = bst.predict(X)
        with _toggle_numpy_path(bst):
            p_numpy = bst.predict(X)
        np.testing.assert_allclose(p_native, p_numpy, rtol=1e-10)

    def test_linear_trees(self):
        rng = np.random.RandomState(2)
        X = rng.randn(4000, 4).astype(np.float32)
        y = np.where(X[:, 0] > 0, 2 * X[:, 1], -X[:, 1]).astype(np.float32)
        bst = lgb.train({"objective": "regression", "num_leaves": 8,
                         "linear_tree": True, "verbosity": -1},
                        lgb.Dataset(X, label=y), 10)
        p_native = bst.predict(X)
        with _toggle_numpy_path(bst):
            p_numpy = bst.predict(X)
        np.testing.assert_allclose(p_native, p_numpy, rtol=1e-8)

    def test_start_num_iteration(self):
        bst, X = self._model()
        p_native = bst.predict(X, start_iteration=5, num_iteration=10)
        with _toggle_numpy_path(bst):
            p_numpy = bst.predict(X, start_iteration=5, num_iteration=10)
        np.testing.assert_allclose(p_native, p_numpy, rtol=1e-10)


class TestDaskSurface:
    def test_estimators_importable(self):
        from lightgbm_tpu.dask import (DaskLGBMClassifier,
                                       DaskLGBMRanker, DaskLGBMRegressor)
        assert DaskLGBMClassifier is not None
        assert DaskLGBMRegressor is not None
        assert DaskLGBMRanker is not None

    def test_raises_without_dask(self):
        from lightgbm_tpu import dask as lgb_dask
        if lgb_dask._DASK_AVAILABLE:
            pytest.skip("dask installed")
        with pytest.raises(ImportError):
            lgb_dask.DaskLGBMClassifier(n_estimators=5)


class TestFileIO:
    """Pluggable file IO (reference VirtualFileReader/Writer,
    file_io.cpp): registered schemes carry model save/load."""

    def test_registered_scheme_round_trip(self):
        import io as _io
        from lightgbm_tpu.utils import file_io

        store = {}

        class MemText(_io.StringIO):
            def __init__(self, path, mode):
                self._p, self._m = path, mode
                super().__init__(store.get(path, "")
                                 if "r" in mode else "")

            def close(self):
                if "w" in self._m:
                    store[self._p] = self.getvalue()
                super().close()

        file_io.register_filesystem("memtest", MemText)
        try:
            r = np.random.RandomState(0)
            X = r.randn(400, 4)
            y = (X[:, 0] > 0).astype(np.float32)
            bst = lgb.train({"objective": "binary", "verbosity": -1},
                            lgb.Dataset(X, label=y), 3)
            bst.save_model("memtest://m.txt")
            assert "memtest://m.txt" in store
            bst2 = lgb.Booster(model_file="memtest://m.txt")
            np.testing.assert_allclose(bst2.predict(X), bst.predict(X),
                                       rtol=1e-7, atol=1e-8)
        finally:
            file_io._SCHEMES.pop("memtest", None)

    def test_unknown_scheme_raises(self):
        from lightgbm_tpu.utils.file_io import open_file
        with pytest.raises(ValueError, match="no filesystem registered"):
            open_file("nosuchscheme://x/y", "r")


class TestNativeBoundarySearch:
    """lgbt_find_numeric_bounds must be mapper-identical to the NumPy
    from_sample path (cext/binning.cpp; reference dataset_loader.cpp
    OMP FindBin loop)."""

    def _compare(self, X, max_bin=63, use_missing=True,
                 zero_as_missing=False):
        from lightgbm_tpu import cext
        from lightgbm_tpu.binning import (BinMapper, _ZERO_THRESHOLD)
        if not cext.available():
            import pytest
            pytest.skip("no native toolchain")
        sample_t = np.ascontiguousarray(X.T, np.float64)
        blist, mtype, minmax, zero_na = cext.find_numeric_bounds(
            sample_t, max_bin, 3, use_missing, zero_as_missing)
        for f in range(X.shape[1]):
            col = sample_t[f]
            nonzero = col[(np.abs(col) > _ZERO_THRESHOLD) | np.isnan(col)]
            ref = BinMapper.from_sample(
                nonzero, X.shape[0], max_bin, 3, use_missing,
                zero_as_missing)
            nat = BinMapper._from_native(
                blist[f], mtype[f], minmax[f], zero_na[f], X.shape[0])
            assert nat.num_bin == ref.num_bin, f
            assert nat.missing_type == ref.missing_type, f
            assert nat.default_bin == ref.default_bin, f
            assert nat.is_trivial == ref.is_trivial, f
            np.testing.assert_allclose(nat.bin_upper_bound,
                                       ref.bin_upper_bound, rtol=0,
                                       atol=0, err_msg=str(f))
            assert nat.min_val == ref.min_val
            assert nat.max_val == ref.max_val
            assert nat.sparse_rate == ref.sparse_rate

    def test_dense_gaussian(self):
        r = np.random.RandomState(0)
        self._compare(r.randn(5000, 8).astype(np.float32))

    def test_sparse_with_nan(self):
        r = np.random.RandomState(1)
        X = np.zeros((4000, 6))
        mask = r.rand(4000, 6) < 0.1
        X[mask] = r.randn(int(mask.sum())) + 1.0
        X[r.rand(4000, 6) < 0.03] = np.nan
        self._compare(X)

    def test_few_distinct_and_constant(self):
        r = np.random.RandomState(2)
        X = np.stack([
            r.randint(0, 4, 3000).astype(np.float64),
            np.full(3000, 2.5),
            np.zeros(3000),
            np.where(r.rand(3000) < 0.5, -1.25, 3.75),
        ], axis=1)
        self._compare(X, max_bin=255)

    def test_zero_as_missing(self):
        r = np.random.RandomState(3)
        X = np.zeros((3000, 4))
        m = r.rand(3000, 4) < 0.4
        X[m] = r.randn(int(m.sum()))
        self._compare(X, zero_as_missing=True)

    def test_negative_heavy(self):
        r = np.random.RandomState(4)
        self._compare(-np.abs(r.randn(4000, 5)) - 0.5, max_bin=31)

    def test_find_bin_mappers_dispatch_equal(self):
        # end-to-end: find_bin_mappers (native fast path) equals the
        # pure-python construction, including a categorical column
        from lightgbm_tpu import binning, cext
        if not cext.available():
            import pytest
            pytest.skip("no native toolchain")
        r = np.random.RandomState(5)
        X = r.randn(3000, 5)
        X[:, 2] = r.randint(0, 7, 3000)
        X[r.rand(3000) < 0.05, 0] = np.nan
        fast = binning.find_bin_mappers(X, max_bin=63,
                                        categorical_features=[2])
        sample_t = np.ascontiguousarray(X.T, np.float64)
        slow = []
        for f in range(5):
            col = sample_t[f]
            nz = col[(np.abs(col) > binning._ZERO_THRESHOLD) |
                     np.isnan(col)]
            slow.append(binning.BinMapper.from_sample(
                nz, 3000, 63, 3, True, False, is_categorical=f == 2))
        for f, (a, b) in enumerate(zip(fast, slow)):
            assert a.num_bin == b.num_bin, f
            assert a.missing_type == b.missing_type, f
            assert a.default_bin == b.default_bin, f
            np.testing.assert_array_equal(
                np.asarray(a.bin_upper_bound),
                np.asarray(b.bin_upper_bound), err_msg=str(f))
            assert a.bin_2_categorical == b.bin_2_categorical, f
