"""Unified observability subsystem (lightgbm_tpu/observability/).

Covers: span nesting + thread safety, Chrome/Perfetto + JSONL trace
round-trips, MFU arithmetic against hand-computed MAC counts, the
Prometheus text endpoint (scraped over HTTP), per-iteration training
telemetry from live boosters (normal and fused paths), compile
accounting, the disabled-path contract (shared null span, empty ring),
and the custom-fobj constant-hessian regression (Booster.update(fobj)
must neutralize the objective's is_constant_hessian gate exactly like
engine.train's objective="none" reset).
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.observability import mfu
from lightgbm_tpu.observability import registry as obs
from lightgbm_tpu.observability.export import prometheus_lines
from lightgbm_tpu.observability.trace import Trace, _NULL_SPAN


@pytest.fixture(autouse=True)
def _obs_state():
    """Each test starts from a clean, disabled registry."""
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


def _data(n=400, f=6, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 7, "learning_rate": 0.2,
          "max_bin": 31, "verbosity": -1, "min_data_in_leaf": 5}


def _mxu_booster(X, y, extra=None):
    """Force the fused-eligible MXU path on CPU (interpret mode) after
    one normal iteration — same trick as test_bench_robustness.py."""
    ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
    bst = lgb.Booster(params=dict(PARAMS, **(extra or {})), train_set=ds)
    bst.update()
    g = bst.gbdt
    g._hist_impl = "mxu"
    g._mxu_interpret = True
    g._fused_run = None
    g._obs_tree_macs = None   # path change invalidates the MAC cache
    return bst


# ---------------------------------------------------------------- spans
class TestSpans:
    def test_nesting_depth_and_parent(self):
        tr = Trace()
        tr.enabled = True
        with tr.span("outer", x=1):
            with tr.span("mid"):
                with tr.span("inner"):
                    pass
        by_name = {s["name"]: s for s in tr.spans()}
        assert by_name["outer"]["depth"] == 0
        assert "parent" not in by_name["outer"]
        assert by_name["mid"]["depth"] == 1
        assert by_name["mid"]["parent"] == "outer"
        assert by_name["inner"]["depth"] == 2
        assert by_name["inner"]["parent"] == "mid"
        assert by_name["outer"]["attrs"] == {"x": 1}
        # completion order: innermost exits (and lands) first
        assert [s["name"] for s in tr.spans()] == \
            ["inner", "mid", "outer"]

    def test_thread_safety_of_nesting(self):
        tr = Trace(capacity=4096)
        tr.enabled = True
        errs = []

        def work(tag):
            try:
                for i in range(50):
                    with tr.span(f"{tag}_outer", i=i):
                        with tr.span(f"{tag}_inner"):
                            pass
            except Exception as exc:  # pragma: no cover
                errs.append(exc)

        threads = [threading.Thread(target=work, args=(f"t{k}",))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        spans = tr.spans()
        assert len(spans) == 4 * 50 * 2
        # per-thread stacks: an inner span's parent is ALWAYS its own
        # thread's outer, never a concurrent thread's
        for s in spans:
            if s["name"].endswith("_inner"):
                assert s["parent"] == s["name"].replace("_inner",
                                                        "_outer")
                assert s["depth"] == 1

    def test_ring_eviction_counts_drops(self):
        tr = Trace(capacity=16)
        tr.enabled = True
        for i in range(30):
            tr.add(f"s{i}", 0.0, 0.001)
        assert len(tr) == 16
        assert tr.dropped == 14
        assert tr.spans()[0]["name"] == "s14"  # oldest evicted

    def test_disabled_returns_shared_null_span(self):
        tr = Trace()
        assert tr.span("a") is _NULL_SPAN
        assert tr.span("b", k=1) is tr.span("c")
        with tr.span("a"):
            pass
        tr.add("manual", 0.0, 1.0)
        assert len(tr) == 0


# --------------------------------------------------------------- export
class TestTraceExport:
    def test_chrome_perfetto_round_trip(self, tmp_path):
        tr = Trace()
        tr.enabled = True
        with tr.span("grow_tree", iteration=3):
            time.sleep(0.001)
        path = tmp_path / "trace.json"
        assert tr.dump(str(path)) == "chrome"
        doc = json.loads(path.read_text())
        assert doc["displayTimeUnit"] == "ms"
        ev = doc["traceEvents"][0]
        assert ev["ph"] == "X"
        assert ev["name"] == "grow_tree"
        assert ev["cat"] == "lightgbm_tpu"
        assert ev["dur"] >= 1000            # microseconds
        assert ev["args"]["iteration"] == 3
        assert {"ts", "pid", "tid"} <= set(ev)

    def test_jsonl_round_trip(self, tmp_path):
        tr = Trace()
        tr.enabled = True
        for i in range(3):
            tr.add("iter", float(i), 0.5, iteration=i)
        path = tmp_path / "trace.jsonl"
        assert tr.dump(str(path)) == "jsonl"
        recs = [json.loads(ln) for ln in
                path.read_text().strip().splitlines()]
        assert len(recs) == 3
        assert [r["attrs"]["iteration"] for r in recs] == [0, 1, 2]
        assert all(r["dur"] == 0.5 for r in recs)


# ------------------------------------------------------------------ mfu
class TestMFU:
    def test_histogram_macs_hand_computed(self):
        # nchan * S * N_pad * F * B_pad, N padded to the row block and
        # B to the 128-lane boundary (histogram_mxu.py docstring)
        macs = mfu.histogram_macs(num_slots=23, num_rows=1000,
                                  num_features=10, bmax=63, nchan=5)
        assert macs == 5 * 23 * 4096 * 10 * 128

    def test_hist_channels_mirror_fits_v2(self):
        assert mfu.hist_channels(double_prec=True) == 5
        assert mfu.hist_channels(double_prec=False) == 4
        assert mfu.hist_channels(quantized=True) == 3
        assert mfu.hist_channels(quantized=True, const_hess=True) == 2
        assert mfu.hist_channels(const_hess=True) == 3

    def test_tree_macs_hand_computed_schedule(self):
        # num_leaves=7, overshoot=2.0 -> L_g=14, s_max=15; doubling
        # schedule 2,4,8,15; subtraction halves slots per pass:
        # 1+2+4+8 = 15, bridge (15+1)//2 = 8 -> 23 slots total
        macs = mfu.tree_macs(num_leaves=7, num_rows=1000,
                             num_features=10, bmax=63, overshoot=2.0)
        assert macs == 5 * 23 * 4096 * 10 * 128

    def test_tree_macs_no_subtraction_no_overshoot(self):
        # overshoot off: s_max = num_leaves + 1 = 8; schedule 2,4,8;
        # full slots 2+4+8 = 14, no bridge
        macs = mfu.tree_macs(num_leaves=7, num_rows=1000,
                             num_features=10, bmax=63, overshoot=0.0,
                             hist_subtraction=False)
        assert macs == 5 * 14 * 4096 * 10 * 128

    def test_achieved_tflops_and_mfu(self, monkeypatch):
        assert mfu.achieved_tflops(0.5e12) == 1.0   # 1 MAC = 2 FLOPs
        assert mfu.mfu_fraction(45.0, 90.0) == 0.5
        assert mfu.mfu_fraction(45.0, 0.0) is None  # unknown peak
        monkeypatch.setenv("LGBM_TPU_PEAK_TFLOPS", "918")
        assert mfu.device_peak_tflops() == 918.0
        assert mfu.mfu_fraction(91.8) == pytest.approx(0.1)

    def test_device_utilization_accumulator(self, monkeypatch):
        monkeypatch.setenv("LGBM_TPU_PEAK_TFLOPS", "100")
        du = mfu.DeviceUtilization()
        du.add(25e12, 1.0, trees=2)   # 25e12 MACs/s = 50 TFLOP/s
        snap = du.snapshot()
        assert snap["trees"] == 2
        assert snap["achieved_tflops"] == pytest.approx(50.0)
        assert snap["mfu"] == pytest.approx(0.5)


# ----------------------------------------------------------- prometheus
class TestPrometheus:
    def test_flattener(self):
        lines = prometheus_lines(
            {"a": 1, "nested": {"b": 2.5, "skip": "str"},
             "flag": True}, "pre")
        assert "# TYPE pre_a gauge" in lines
        assert "pre_a 1" in lines
        assert "pre_nested_b 2.5" in lines
        assert "pre_flag 1" in lines
        assert not any("skip" in ln for ln in lines)

    def test_labels_and_name_sanitizing(self):
        lines = prometheus_lines({"p50 ms": 1.5}, "m",
                                 labels={"model": 'a"b'})
        assert 'm_p50_ms{model="a\\"b"} 1.5' in lines

    def test_registry_text_scrapeable_totals(self):
        obs.enable()
        obs.compiles.record("fused_train", 2.0, compiled=True)
        obs.compiles.record("fused_train", 0.0, compiled=False)
        text = obs.prometheus_text()
        assert "lightgbm_tpu_observability_enabled 1" in text
        assert "lightgbm_tpu_compiles_compile_count 1" in text
        assert "lightgbm_tpu_compiles_hit_count 1" in text
        assert ("lightgbm_tpu_compiles_entries_fused_train_compiles 1"
                in text)

    def test_serving_metrics_http_endpoint(self):
        from lightgbm_tpu.serving import Server
        X, y = _data()
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        bst = lgb.Booster(params=dict(PARAMS), train_set=ds)
        for _ in range(3):
            bst.update()
        with Server(min_bucket=16, max_bucket=64) as srv:
            srv.load_model("m1", booster=bst)
            srv.predict("m1", X[:10])
            msrv = srv.start_metrics_server(port=0)
            assert msrv.port > 0
            # idempotent: second call returns the running endpoint
            assert srv.start_metrics_server() is msrv
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{msrv.port}/metrics",
                timeout=10).read().decode()
            assert "# TYPE" in body
            assert 'lightgbm_tpu_serving_model_requests{model="m1"} 1' \
                in body
            assert "lightgbm_tpu_serving_engine_device_batches 1" \
                in body
            ok = urllib.request.urlopen(
                f"http://127.0.0.1:{msrv.port}/healthz",
                timeout=10).read()
            assert ok == b"ok\n"
            snap = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{msrv.port}/snapshot",
                timeout=10).read())
            assert snap["models"]["m1"]["requests"] == 1
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{msrv.port}/nope", timeout=10)
        # server close shuts the endpoint down
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{msrv.port}/healthz", timeout=2)


# ------------------------------------------------------ train telemetry
class TestTrainingTelemetry:
    def test_per_iteration_records(self):
        X, y = _data()
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        bst = lgb.Booster(params=dict(PARAMS, observe=True,
                                      observe_norms=True),
                          train_set=ds)
        assert obs.enabled
        for _ in range(4):
            bst.update()
        snap = obs.snapshot()
        assert snap["training"]["iterations"] == 4
        assert snap["training"]["trees"] == 4
        last = snap["training"]["last"]
        assert last["iteration"] == 3
        assert last["wall_s"] > 0
        assert "tree_train" in last["phases"]
        assert last["grad_norm"] > 0
        assert last["hess_norm"] > 0
        assert last["leaves"] >= 2
        # span trace mirrors the iterations
        names = [s["name"] for s in obs.trace.spans()]
        assert names.count("train_iter") == 4

    def test_fused_block_record_and_compile_accounting(self):
        X, y = _data(seed=8)
        obs.enable()
        bst = _mxu_booster(X, y)
        bst.update_batch(3)
        last = obs.training.last()
        assert last["fused"] is True
        assert last["iterations"] == 3
        assert last["trees"] == 3
        # the forced-MXU booster has an analytic MAC model -> MFU
        # accumulates estimated MACs for the block
        assert last["estimated_macs"] > 0
        comp = obs.compiles.snapshot()
        assert comp["fused_train"]["compiles"] == 1
        assert comp["fused_train"]["compile_seconds"] > 0
        bst.update_batch(2)
        comp = obs.compiles.snapshot()
        assert comp["fused_train"]["compiles"] == 1
        assert comp["fused_train"]["hits"] == 1
        du = obs.mfu.snapshot()
        assert du["estimated_macs"] == obs.tree_macs_for(bst.gbdt) * 5
        assert du["trees"] == 5

    def test_counter_deltas_fold_into_records(self):
        X, y = _data(seed=9)
        obs.enable()
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        bst = lgb.Booster(params=dict(PARAMS), train_set=ds)
        bst.update()
        obs.counters.inc("guard_trips")
        bst.update()
        recs = obs.training.records()
        assert recs[-1]["counters"]["guard_trips"] == 1
        bst.update()
        assert "counters" not in obs.training.records()[-1]

    def test_disabled_path_records_nothing(self):
        X, y = _data(seed=10)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        bst = lgb.Booster(params=dict(PARAMS), train_set=ds)
        for _ in range(3):
            bst.update()
        assert obs.training.iterations == 0
        assert len(obs.trace) == 0
        assert obs.compiles.snapshot() == {}

    def test_disabled_span_overhead_smoke(self):
        # the off path is one attribute read + branch; 10k no-op spans
        # must be far under one training iteration's wall (~ms). Loose
        # bound: 50ms even on a loaded CI box.
        t0 = time.perf_counter()
        for _ in range(10_000):
            with obs.trace.span("x"):
                pass
        assert time.perf_counter() - t0 < 0.05
        assert len(obs.trace) == 0


# -------------------------------------------- custom-fobj const-hessian
class TestCustomObjectiveConstHessian:
    def _scaled_l2(self, y):
        def fobj(score, ds_):
            return 2.0 * (score - y), np.full_like(score, 2.0)
        return fobj

    def test_update_fobj_neutralizes_const_hessian_gate(self):
        X, y = _data(seed=11)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        bst = lgb.Booster(params=dict(PARAMS, objective="regression"),
                          train_set=ds)
        assert bst.gbdt._const_hessian() == 1.0
        bst.update(fobj=self._scaled_l2(y))
        # the objective still claims constant hessians, but the
        # gradients trained on are the user's — the gate must be off
        # (reference mirrors this by resetting objective to "none")
        assert bst.gbdt._const_hessian() == 0.0

    def test_update_fobj_matches_objective_none_on_mxu(self):
        # pre-fix failure mode: objective="regression" + update(fobj)
        # kept const_hessian=1.0, so the MXU kernel dropped the hessian
        # channel and reconstructed h as the row count (1.0/row) —
        # silently wrong for any fobj with hessians != count. With the
        # gate fixed, the model must be identical to the one trained
        # with objective="none" (the engine.train normalization).
        X, y = _data(seed=12)
        fobj = self._scaled_l2(y)
        boosters = []
        for objective in ("regression", "none"):
            ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
            bst = lgb.Booster(
                params=dict(PARAMS, objective=objective,
                            boost_from_average=False), train_set=ds)
            bst.update(fobj=fobj)      # iteration 0: normal path
            g = bst.gbdt
            g._hist_impl = "mxu"
            g._mxu_interpret = True
            g._fused_run = None
            for _ in range(3):
                bst.update(fobj=fobj)  # MXU path, custom hessians
            boosters.append(bst)
        a, b = boosters
        assert a.gbdt._const_hessian() == b.gbdt._const_hessian() == 0.0
        # identical trees; only the objective= header lines may differ
        def _trees(s):
            return "\n".join(ln for ln in s.splitlines()
                             if "objective" not in ln)
        assert _trees(a.model_to_string()) == _trees(b.model_to_string())
        np.testing.assert_array_equal(np.asarray(a.gbdt.train_score),
                                      np.asarray(b.gbdt.train_score))
