"""4-bit packed bin storage (reference 4-bit DenseBin, dense_bin.hpp:42).

Packing is a pure storage transform: the MXU kernels unpack nibbles in
VMEM, so packed and unpacked training must produce bit-identical trees.
Fast layout tests run in the default tier; kernel-parity tests ride the
slow tier (Pallas interpret mode).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.data import BinnedDataset, Metadata
from lightgbm_tpu.learner.grower_mxu import grow_tree_mxu
from lightgbm_tpu.learner.histogram_mxu import (
    build_histograms_mxu_v2, fused_route_hist_mxu, pack_bins_4bit,
    pack_route_tables, route_rows_mxu, unpack_bins_4bit)
from lightgbm_tpu.learner.split import SplitHyperParams


def _small_bin_data(n=3000, f=7, seed=0, with_nan=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    if with_nan:
        X[rng.rand(n) < 0.05, 1] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.5 * np.nan_to_num(X[:, 1]) > 0) \
        .astype(np.float32)
    ds = BinnedDataset.from_raw(X, Metadata(n, label=y), max_bin=15)
    assert int(ds.num_bins.max()) <= 16
    p = np.full(n, 0.5, np.float32)
    return ds, jnp.asarray(p - y), jnp.asarray(p * (1 - p))


class TestPackLayout:
    def test_roundtrip_even_odd(self):
        rng = np.random.RandomState(3)
        for f in (1, 2, 7, 8, 15):
            bins = rng.randint(0, 16, size=(64, f)).astype(np.uint8)
            packed = pack_bins_4bit(bins)
            assert packed.shape == (64, (f + 1) // 2)
            np.testing.assert_array_equal(
                unpack_bins_4bit(packed, f), bins)

    def test_roundtrip_device(self):
        rng = np.random.RandomState(4)
        bins = rng.randint(0, 16, size=(32, 5)).astype(np.uint8)
        packed = pack_bins_4bit(jnp.asarray(bins))
        np.testing.assert_array_equal(
            np.asarray(unpack_bins_4bit(packed, 5)), bins)

    def test_split_nibble_layout(self):
        # feature j < Fh in column j's low nibble, Fh+j in the high one
        bins = np.arange(8, dtype=np.uint8).reshape(1, 8) % 16
        packed = pack_bins_4bit(bins)
        fh = 4
        for j in range(8):
            col = j if j < fh else j - fh
            nib = (packed[0, col] >> 4) if j >= fh else (packed[0, col] & 15)
            assert nib == bins[0, j]


@pytest.mark.slow
class TestPackedKernels:
    def test_hist_v2_parity(self):
        ds, g, h = _small_bin_data()
        bins = jnp.asarray(ds.bins)
        cnt = jnp.ones(ds.num_data, jnp.float32)
        slot = jnp.asarray(
            np.random.RandomState(0).randint(-1, 8, size=ds.num_data)
            .astype(np.int32))
        bmax = int(ds.num_bins.max())
        h_ref = build_histograms_mxu_v2(bins, g, h, cnt, slot, num_slots=8,
                                        bmax=bmax, interpret=True)
        h_pk = build_histograms_mxu_v2(
            pack_bins_4bit(bins), g, h, cnt, slot, num_slots=8, bmax=bmax,
            num_features=ds.num_features, interpret=True)
        np.testing.assert_array_equal(np.asarray(h_ref), np.asarray(h_pk))

    def _route_tables(self, ds, m_pad=128):
        f = ds.num_features
        m1 = 8
        w_cat = (int(ds.num_bins.max()) + 31) // 32
        split_mask = jnp.zeros(m1, bool).at[0].set(True)
        feat = jnp.zeros(m1, jnp.int32).at[0].set(f - 1)  # high nibble
        thr = jnp.zeros(m1, jnp.int32).at[0].set(7)
        child_l = jnp.full(m1, m1 - 1, jnp.int32).at[0].set(1)
        child_r = jnp.full(m1, m1 - 1, jnp.int32).at[0].set(2)
        slot_of = jnp.full(m1, -1, jnp.int32).at[1].set(0).at[2].set(1)
        return pack_route_tables(
            split_mask, feat, thr, jnp.zeros(m1, bool),
            jnp.zeros(m1, bool), child_l, child_r, slot_of,
            jnp.zeros((m1, w_cat), jnp.uint32), m_pad,
            int(ds.num_bins.max()))

    def test_route_parity(self):
        ds, _, _ = _small_bin_data(with_nan=True, seed=5)
        bins = jnp.asarray(ds.bins)
        tbl, member = self._route_tables(ds)
        feat_tbl = jnp.stack(
            [jnp.asarray(ds.num_bins, jnp.float32),
             jnp.asarray(ds.missing_types == 2, jnp.float32)], axis=1)
        node0 = jnp.zeros(ds.num_data, jnp.int32)
        rn_ref, rs_ref = route_rows_mxu(bins, node0, tbl, member,
                                        feat_tbl, interpret=True)
        rn_pk, rs_pk = route_rows_mxu(
            pack_bins_4bit(bins), node0, tbl, member, feat_tbl,
            num_features=ds.num_features, interpret=True)
        np.testing.assert_array_equal(np.asarray(rn_ref), np.asarray(rn_pk))
        np.testing.assert_array_equal(np.asarray(rs_ref), np.asarray(rs_pk))

    def test_fused_parity(self):
        ds, g, h = _small_bin_data(with_nan=True, seed=6)
        bins = jnp.asarray(ds.bins)
        cnt = jnp.ones(ds.num_data, jnp.float32)
        tbl, member = self._route_tables(ds)
        feat_tbl = jnp.stack(
            [jnp.asarray(ds.num_bins, jnp.float32),
             jnp.asarray(ds.missing_types == 2, jnp.float32)], axis=1)
        node0 = jnp.zeros(ds.num_data, jnp.int32)
        bmax = int(ds.num_bins.max())
        h_ref, rn_ref = fused_route_hist_mxu(
            bins, g, h, cnt, node0, tbl, member, feat_tbl,
            num_slots=4, bmax=bmax, interpret=True)
        h_pk, rn_pk = fused_route_hist_mxu(
            pack_bins_4bit(bins), g, h, cnt, node0, tbl, member, feat_tbl,
            num_slots=4, bmax=bmax, num_features=ds.num_features,
            interpret=True)
        np.testing.assert_array_equal(np.asarray(h_ref), np.asarray(h_pk))
        np.testing.assert_array_equal(np.asarray(rn_ref), np.asarray(rn_pk))

    def test_grower_identical_trees(self):
        ds, g, h = _small_bin_data(with_nan=True, seed=7)
        cnt = jnp.ones(ds.num_data, jnp.float32)
        args_tail = (cnt, jnp.ones(ds.num_features, jnp.float32),
                     jnp.asarray(ds.num_bins),
                     jnp.asarray(ds.missing_types == 2),
                     jnp.asarray(ds.is_categorical))
        kw = dict(num_leaves=15, max_depth=0,
                  hp=SplitHyperParams(min_data_in_leaf=20),
                  bmax=int(ds.num_bins.max()), interpret=True,
                  overshoot=2.0)
        t_ref, r_ref = grow_tree_mxu(jnp.asarray(ds.bins), g, h,
                                     *args_tail, **kw)
        t_pk, r_pk = grow_tree_mxu(pack_bins_4bit(jnp.asarray(ds.bins)),
                                   g, h, *args_tail, packed4=True, **kw)
        nn = int(t_ref.num_nodes)
        assert int(t_ref.num_leaves) == int(t_pk.num_leaves)
        for fld in ("split_feature", "threshold_bin", "left", "right",
                    "default_left"):
            np.testing.assert_array_equal(
                np.asarray(getattr(t_ref, fld))[:nn],
                np.asarray(getattr(t_pk, fld))[:nn], err_msg=fld)
        np.testing.assert_array_equal(
            np.asarray(t_ref.leaf_value)[:nn],
            np.asarray(t_pk.leaf_value)[:nn])
        np.testing.assert_array_equal(np.asarray(r_ref), np.asarray(r_pk))
