"""Scan-vs-argsort partition parity (`make kernels` / `make perf`).

The round-6 partition contract (docs/PerfNotes.md): partition_rows'
"scan" implementation — stable rank via blocked prefix sums over the
per-slot counts the router already emits — produces the IDENTICAL
permutation the retained stable argsort oracle produces, hence
bit-identical (block_slot, src) layouts and byte-equal model.txt
through every downstream consumer. The adversarial shapes here are the
ones that break naive rank constructions: empty slots (zero-count
prefix entries), all rows in one slot (single giant run), a single
row, N not a multiple of row_block (padded tail rows must rank AFTER
every real row), and duplicate-heavy slot vectors (long equal runs
where only a STABLE rank preserves source order).

The perf-marked subset asserts the structural claims behind the win —
counts reuse (routing + counting + partitioning is one sweep) and the
absence of any sort primitive in the scan path's jaxpr — with no
wall-clock thresholds (tier-1 stays timing-independent).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.kernels

import jax
import jax.numpy as jnp

from lightgbm_tpu.analysis.tracecheck import has_sort_primitive
from lightgbm_tpu.learner.histogram_pallas import (_stable_order_scan,
                                                   partition_rows)


def _parity(row_slot, *, num_slots, row_block, counts=None):
    """Assert scan and argsort return byte-identical layouts. (auto is
    asserted to BE scan once, in test_auto_resolves_to_scan — running
    it per-case would just re-dispatch the scan path a third time.)"""
    outs = {}
    for impl in ("argsort", "scan"):
        bs, src = partition_rows(jnp.asarray(row_slot, jnp.int32),
                                 num_slots=num_slots, row_block=row_block,
                                 counts=counts, impl=impl)
        outs[impl] = (np.asarray(bs), np.asarray(src))
    for a, b in zip(outs["argsort"], outs["scan"]):
        assert a.tobytes() == b.tobytes()
    return outs["argsort"]


class TestAdversarialParity:
    def test_empty_slots(self):
        # slots 1, 3, 5 get zero rows: their prefix-sum bases collapse
        # onto the next live slot's base
        rng = np.random.RandomState(0)
        slot = rng.choice([0, 2, 4, 6], size=777)
        _parity(slot, num_slots=8, row_block=64)

    def test_all_rows_one_slot(self):
        _parity(np.full(513, 3), num_slots=8, row_block=64)

    def test_single_row(self):
        _parity(np.array([2]), num_slots=4, row_block=8)

    def test_n_not_multiple_of_row_block(self):
        rng = np.random.RandomState(1)
        # also not a multiple of the scan's internal block size
        _parity(rng.randint(0, 6, size=5001), num_slots=6, row_block=128)

    def test_duplicate_heavy(self):
        # long equal runs: an unstable rank would permute within-slot
        # order and change which rows land in which block
        rng = np.random.RandomState(2)
        slot = np.repeat(rng.randint(0, 4, size=40), 100)
        _parity(slot, num_slots=4, row_block=32)

    def test_parked_rows_go_to_trash_slot(self):
        rng = np.random.RandomState(3)
        slot = rng.randint(-1, 5, size=900)   # -1 = parked
        bs, src = _parity(slot, num_slots=5, row_block=64)
        # parked rows appear only in trash-slot blocks
        trash_positions = np.repeat(bs == 5, 64)
        real = src[~trash_positions]
        real = real[real < 900]
        assert np.all(np.asarray(slot)[real] >= 0)

    def test_unknown_impl_raises(self):
        with pytest.raises(ValueError, match="unknown partition impl"):
            partition_rows(jnp.zeros(8, jnp.int32), num_slots=2,
                           row_block=8, impl="radix")

    def test_auto_resolves_to_scan(self):
        rng = np.random.RandomState(9)
        slot = jnp.asarray(rng.randint(0, 5, 300), jnp.int32)
        a = partition_rows(slot, num_slots=5, row_block=32, impl="auto")
        s = partition_rows(slot, num_slots=5, row_block=32, impl="scan")
        for x, y in zip(a, s):
            assert np.asarray(x).tobytes() == np.asarray(y).tobytes()


@pytest.mark.perf
class TestScanStructure:
    """Microbench-shaped assertions: the structural facts behind the
    round-6 numbers, with no wall-clock thresholds."""

    def test_counts_reuse_is_bit_identical(self):
        # the route_rows_mxu(emit_counts=True) counts replace the
        # segment_sum: same bits either way, one less O(N) pass
        rng = np.random.RandomState(4)
        slot = rng.randint(-1, 7, size=3000)
        live = np.bincount(slot[slot >= 0], minlength=7).astype(np.int32)
        a = _parity(slot, num_slots=7, row_block=128)
        b = _parity(slot, num_slots=7, row_block=128,
                    counts=jnp.asarray(live))
        for x, y in zip(a, b):
            assert x.tobytes() == y.tobytes()

    def test_scan_path_has_no_sort_primitive(self):
        # shared predicate with TRACE001 (analysis.tracecheck): the
        # same walk the lint-time contract checker runs over the
        # manifest entry; the argsort oracle doubles as its positive
        # control
        slot = jnp.asarray(np.random.RandomState(5).randint(0, 6, 2048),
                           jnp.int32)

        def scan_part(s):
            return partition_rows(s, num_slots=6, row_block=128,
                                  impl="scan")

        def argsort_part(s):
            return partition_rows(s, num_slots=6, row_block=128,
                                  impl="argsort")

        assert not has_sort_primitive(jax.make_jaxpr(scan_part)(slot))
        assert has_sort_primitive(jax.make_jaxpr(argsort_part)(slot))

    def test_stable_rank_matches_argsort_rank(self):
        # _stable_order_scan directly vs the stable sort, with tail
        # padding crossing the internal scan block boundary
        rng = np.random.RandomState(6)
        for n in (1, 17, 4096, 4097, 9000):
            slot = jnp.asarray(rng.randint(0, 5, n), jnp.int32)
            counts = jax.ops.segment_sum(jnp.ones(n, jnp.int32), slot,
                                         num_segments=6)
            start = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 jnp.cumsum(counts)[:-1].astype(jnp.int32)])
            got = np.asarray(_stable_order_scan(slot, start, 5))
            want = np.asarray(jnp.argsort(slot))
            assert got.tobytes() == want.tobytes(), n


@pytest.mark.slow
class TestFusedModelParity:
    """Byte-equal model.txt through the fused multi-tree path with the
    pallas scatter backend (the consumer that actually partitions)."""

    def _train(self, partition_impl):
        import lightgbm_tpu as lgb
        rng = np.random.RandomState(7)
        X = rng.randn(500, 5).astype(np.float32)
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
        params = {"objective": "binary", "num_leaves": 7,
                  "learning_rate": 0.2, "max_bin": 31, "verbosity": -1,
                  "min_data_in_leaf": 5, "use_quantized_grad": True,
                  "hist_backend": "pallas",
                  "partition_impl": partition_impl}
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        bst = lgb.Booster(params=params, train_set=ds)
        bst.update()
        g = bst.gbdt
        g._hist_impl = "mxu"
        g._mxu_interpret = True
        g._fused_run = None
        bst.update_batch(3)          # the fused scan dispatch
        return "\n".join(
            ln for ln in bst.model_to_string().splitlines()
            if not ln.startswith("[partition_impl:"))

    def test_byte_identical_scan_vs_argsort(self):
        assert self._train("scan") == self._train("argsort")
