"""Pipelined training executor (lightgbm_tpu/pipeline/) parity suite.

The acceptance bar: `pipeline=true` must train the byte-identical model
of the serial block loop (`pipeline=false`, the parity oracle) across
the whole matrix — regression/binary/multiclass, bagging, GOSS, early
stop mid-block, checkpoint/resume interop — because the fused scan is
iteration-exact, so any block partition (and any dispatch/finalize
interleaving) trains the same trees. Model comparisons strip the
serialized `[pipeline*` / `[fused_block_size` param lines: dispatch
granularity is config, not model content (same idiom as
tests/test_fused.py).

Eval-path fidelity: `pipeline_device_eval=false` (host metrics) must be
EXACTLY identical to the serial loop, history included; the default
device-eval path computes metric values in f32 where the host path is
f64, so histories agree to ~1e-6 relative while models and
best_iteration stay exact (docs/Performance.md).

The fast half (scheduler, stats, device-eval support matrix, the CPU
per-iteration fallback through the executor) runs in tier 1; the
engine-level matrix forces the MXU interpret path on CPU and is slow.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu import callback as cb
from lightgbm_tpu import engine as engine_mod
from lightgbm_tpu.pipeline import (AdaptiveBlockScheduler, PipelineStats,
                                   run_pipelined)
from lightgbm_tpu.pipeline.device_eval import build_device_eval
from lightgbm_tpu.reliability.checkpoint import latest_checkpoint

pytestmark = pytest.mark.pipeline

PARAMS = {"objective": "binary", "num_leaves": 7, "learning_rate": 0.2,
          "max_bin": 31, "verbosity": -1, "min_data_in_leaf": 5}


def _data(n=600, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


def _noisy_valid(n=200, f=5, seed=14):
    rng = np.random.RandomState(seed)
    Xv = rng.randn(n, f).astype(np.float32)
    yv = (Xv[:, 0] + 1.5 * rng.randn(n) > 0).astype(np.float32)
    return Xv, yv


def _strip(text):
    """Model text minus the dispatch-granularity params."""
    return [ln for ln in text.splitlines()
            if not ln.startswith("[pipeline")
            and not ln.startswith("[fused_block_size")]


class _MxuBooster(lgb.Booster):
    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        if self.gbdt is not None:  # model-file/str load has no trainer
            self.gbdt._hist_impl = "mxu"   # force fused eligibility on CPU
            self.gbdt._mxu_interpret = True


@pytest.fixture
def mxu_engine(monkeypatch):
    monkeypatch.setattr(engine_mod, "Booster", _MxuBooster)
    return engine_mod


# ----------------------------------------------------------------------
# fast tier: scheduler, stats, device-eval support matrix, CPU fallback
class TestAdaptiveBlockScheduler:
    def test_base_and_remaining_caps(self):
        s = AdaptiveBlockScheduler(5, adaptive=False)
        assert s.next_block(100) == 5
        assert s.next_block(3) == 3
        assert s.next_block(1) == 1

    def test_adaptive_grows_toward_target(self):
        s = AdaptiveBlockScheduler(5, adaptive=True, target_ms=1000.0,
                                   max_block=200)
        s.observe(5, 0.05)          # 100 iterations/sec
        assert s.next_block(1000) == 100   # rate * 1.0 s
        assert s.next_block(30) == 30      # remaining still caps

    def test_max_block_and_stopping_rounds_cap(self):
        s = AdaptiveBlockScheduler(5, adaptive=True, target_ms=1000.0,
                                   max_block=40, stopping_rounds=8)
        s.observe(5, 0.01)          # 500 iterations/sec -> wants 500
        # early-stopping alignment wins over the rate target
        assert s.next_block(1000) == 8
        s2 = AdaptiveBlockScheduler(5, adaptive=True, target_ms=1000.0,
                                    max_block=40)
        s2.observe(5, 0.01)
        assert s2.next_block(1000) == 40

    def test_compile_blocks_excluded_from_rate(self):
        s = AdaptiveBlockScheduler(5, adaptive=True, target_ms=1000.0,
                                   max_block=200)
        s.observe(5, 5.0, compiled=True)   # compile wall: ignored
        assert s.next_block(1000) == 5     # no rate yet -> base
        s.observe(5, 0.05)
        assert s.next_block(1000) == 100

    def test_never_exceeds_remaining_nor_shrinks_below_one(self):
        s = AdaptiveBlockScheduler(5, adaptive=True, target_ms=1.0)
        s.observe(5, 100.0)          # glacial rate -> wants < base
        assert s.next_block(100) == 5   # base is the floor
        assert s.next_block(2) == 2


class TestPipelineStats:
    def test_overlap_frac_and_dict(self):
        st = PipelineStats()
        st.add(5, host_ms=30.0, device_ms=100.0)
        st.add(5, host_ms=20.0, device_ms=100.0)
        assert st.blocks == 2 and st.iterations == 10
        assert st.overlap_frac == pytest.approx(0.25)
        d = st.as_dict()
        assert d["block_sizes"] == [5, 5]
        assert d["host_ms"] == [30.0, 20.0]
        assert d["device_ms"] == [100.0, 100.0]
        assert d["overlap_frac"] == pytest.approx(0.25)

    def test_overlap_frac_clamped_and_empty(self):
        st = PipelineStats()
        assert st.overlap_frac == 0.0
        st.add(1, host_ms=500.0, device_ms=100.0)
        assert st.overlap_frac == 1.0


class TestDeviceEvalSupport:
    def _valid_booster(self, metric):
        X, y = _data(seed=3)
        Xv, yv = _data(n=200, seed=4)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        bst = lgb.Booster(params={**PARAMS, "metric": metric},
                          train_set=ds)
        bst.add_valid(lgb.Dataset(Xv, label=yv), "v")
        bst.update()
        return bst

    def test_pointwise_metrics_supported(self):
        bst = self._valid_booster("binary_logloss,binary_error")
        assert build_device_eval(bst) is not None

    def test_rank_family_falls_back_to_host(self):
        # all-or-nothing: one sort-based metric anywhere disables the
        # device path for the whole run
        assert build_device_eval(self._valid_booster("auc")) is None
        assert build_device_eval(
            self._valid_booster("l2,auc")) is None

    def test_device_values_match_host_metrics(self):
        bst = self._valid_booster("binary_logloss,binary_error")
        bst.update()
        dev = build_device_eval(bst)
        vs = jnp.asarray(bst.gbdt.valid_scores[0])
        mx = dev.dispatch([jnp.stack([vs, vs])])
        mhost = [np.asarray(a) for a in mx]
        got = {(vn, mn): v for vn, mn, v, _ in dev.evlist_at(mhost, 1)}
        want = {(vn, mn): v for vn, mn, v, _ in bst.eval_valid()}
        assert set(got) == set(want)
        for key, v in want.items():
            assert got[key] == pytest.approx(v, rel=1e-5, abs=1e-7), key


class TestExecutorCpuFallback:
    """run_pipelined over the ineligible (scatter) path: every dispatch
    degrades to per-iteration handles, the executor must still schedule
    correctly and train the identical model."""

    def test_fallback_parity_and_stats(self):
        X, y = _data(seed=7)
        mk = lambda: lgb.Booster(
            params=dict(PARAMS),
            train_set=lgb.Dataset(X, label=y, params={"max_bin": 31}))
        a, b = mk(), mk()
        run_pipelined(a, start_iter=0, num_boost_round=5, base_block=2,
                      run_callbacks=lambda i, ev: None, has_valid=False)
        b.update_batch(5)
        assert a.current_iteration() == b.current_iteration() == 5
        assert a.model_to_string() == b.model_to_string()
        st = a.gbdt._pipeline_stats
        assert st.blocks >= 1
        assert st.iterations == 5
        assert sum(st.block_sizes) == 5

    def test_callback_cadence_is_per_iteration(self):
        X, y = _data(seed=8)
        bst = lgb.Booster(
            params=dict(PARAMS),
            train_set=lgb.Dataset(X, label=y, params={"max_bin": 31}))
        seen = []
        run_pipelined(bst, start_iter=0, num_boost_round=6, base_block=3,
                      run_callbacks=lambda i, ev: seen.append(i),
                      has_valid=False)
        assert seen == [0, 1, 2, 3, 4, 5]

    def test_observability_pipeline_family(self):
        from lightgbm_tpu.observability import registry as obs
        X, y = _data(seed=9)
        bst = lgb.Booster(
            params=dict(PARAMS),
            train_set=lgb.Dataset(X, label=y, params={"max_bin": 31}))
        obs.reset()
        obs.enable()
        try:
            run_pipelined(bst, start_iter=0, num_boost_round=4,
                          base_block=2,
                          run_callbacks=lambda i, ev: None,
                          has_valid=False)
            snap = obs.snapshot()["pipeline"]
            assert snap["blocks"] >= 1
            assert snap["iterations"] == 4
            assert 0.0 <= snap["overlap_frac"] <= 1.0
            assert "lightgbm_tpu_pipeline" in obs.prometheus_text()
        finally:
            obs.disable()
            obs.reset()

    def test_pipeline_params_defaults(self):
        X, y = _data(seed=10)
        bst = lgb.Booster(
            params=dict(PARAMS),
            train_set=lgb.Dataset(X, label=y, params={"max_bin": 31}))
        cfg = bst.config
        assert cfg.pipeline is True
        assert cfg.pipeline_device_eval is True
        assert cfg.pipeline_adaptive_blocks is True
        assert cfg.pipeline_target_block_ms > 0
        assert cfg.pipeline_max_block >= 1


# ----------------------------------------------------------------------
# slow tier: engine-level byte parity on the forced-MXU interpret path
def _train(mxu, params, data, rounds, valid=None, history=None,
           callbacks=None):
    X, y = data
    cbs = list(callbacks or [])
    if history is not None:
        cbs.append(cb.record_evaluation(history))
    return mxu.train(
        params, lgb.Dataset(X, label=y, params={"max_bin": 31}),
        num_boost_round=rounds,
        valid_sets=[lgb.Dataset(valid[0], label=valid[1])]
        if valid is not None else None,
        callbacks=cbs or None)


def _flatten(history):
    return {(vn, mn): vals for vn, d in history.items()
            for mn, vals in d.items()}


@pytest.mark.slow
class TestEnginePipelineParity:
    @pytest.mark.parametrize("task_params,mkdata", [
        (dict(PARAMS), _data),
        ({**PARAMS, "objective": "regression"}, _data),
        ({**PARAMS, "objective": "multiclass", "num_class": 3},
         lambda: (_data()[0],
                  (_data()[0][:, 0] > 0).astype(np.float32) +
                  (_data()[0][:, 1] > 0.5))),
    ], ids=["binary", "regression", "multiclass"])
    def test_model_parity_device_eval(self, mxu_engine, task_params,
                                      mkdata):
        data, valid = mkdata(), _noisy_valid()
        if task_params.get("num_class", 1) > 1:
            rng = np.random.RandomState(15)
            Xv = rng.randn(200, 5).astype(np.float32)
            valid = (Xv, (Xv[:, 0] > 0).astype(np.float32) +
                     (Xv[:, 1] > 0.5))
        models = []
        for pipeline in (True, False):
            bst = _train(mxu_engine,
                         {**task_params, "fused_block_size": 4,
                          "pipeline": pipeline}, data, 10, valid=valid)
            if pipeline:
                st = getattr(bst.gbdt, "_pipeline_stats", None)
                assert st is not None and st.blocks >= 1, \
                    "pipeline did not engage — test is vacuous"
                assert st.iterations == 10
            models.append(bst.model_to_string())
        assert _strip(models[0]) == _strip(models[1])

    @pytest.mark.parametrize("extra", [
        {"bagging_fraction": 0.7, "bagging_freq": 2},
        {"boosting": "goss", "top_rate": 0.3, "other_rate": 0.3},
    ], ids=["bagging", "goss"])
    def test_model_parity_sampling(self, mxu_engine, extra):
        data, valid = _data(seed=6), _noisy_valid(seed=16)
        models = []
        for pipeline in (True, False):
            bst = _train(mxu_engine,
                         {**PARAMS, **extra, "fused_block_size": 4,
                          "pipeline": pipeline}, data, 10, valid=valid)
            models.append(bst.model_to_string())
        assert _strip(models[0]) == _strip(models[1])

    def test_host_eval_mode_exactly_matches_serial(self, mxu_engine):
        # pipeline_device_eval=false routes metrics through the same
        # f64 host path as the oracle: byte parity AND exact history
        data, valid = _data(seed=5), _noisy_valid(seed=17)
        out = []
        for pipeline in (True, False):
            hist = {}
            bst = _train(mxu_engine,
                         {**PARAMS, "fused_block_size": 4,
                          "pipeline": pipeline,
                          "pipeline_device_eval": False},
                         data, 10, valid=valid, history=hist)
            out.append((bst.model_to_string(), hist))
        assert _strip(out[0][0]) == _strip(out[1][0])
        assert out[0][1] == out[1][1]   # float-exact history

    def test_device_eval_history_close_to_host(self, mxu_engine):
        data, valid = _data(seed=5), _noisy_valid(seed=17)
        hists = []
        for device_eval in (True, False):
            hist = {}
            _train(mxu_engine,
                   {**PARAMS, "fused_block_size": 4,
                    "pipeline_device_eval": device_eval},
                   data, 10, valid=valid, history=hist)
            hists.append(_flatten(hist))
        dev, host = hists
        assert set(dev) == set(host)
        for key in host:
            np.testing.assert_allclose(dev[key], host[key], rtol=1e-5,
                                       err_msg=str(key))

    @pytest.mark.parametrize("device_eval", [True, False],
                             ids=["device-eval", "host-eval"])
    def test_early_stop_mid_block_parity(self, mxu_engine, device_eval):
        data, valid = _data(seed=13), _noisy_valid(seed=14)
        results = []
        for pipeline in (True, False):
            bst = _train(mxu_engine,
                         {**PARAMS, "early_stopping_round": 2,
                          "fused_block_size": 5, "pipeline": pipeline,
                          "pipeline_device_eval": device_eval},
                         data, 25, valid=valid)
            results.append(bst)
        a, b = results
        assert a.best_iteration == b.best_iteration
        assert a.current_iteration() == b.current_iteration()
        assert _strip(a.model_to_string()) == _strip(b.model_to_string())
        if device_eval:
            for key in dict(b.best_score):
                assert dict(a.best_score)[key] == pytest.approx(
                    dict(b.best_score)[key], rel=1e-5)
        else:
            assert dict(a.best_score) == dict(b.best_score)
        # the stop must engage before the round budget, mid-block,
        # or this proves nothing about the rollback protocol
        assert a.current_iteration() < 25

    def test_checkpoint_resume_into_pipeline(self, mxu_engine, tmp_path):
        # checkpoint callbacks are not block-safe, so run A trains
        # non-pipelined; resuming WITHOUT the callback re-engages the
        # pipeline for the tail and must land on the byte-identical
        # model of a straight pipelined run
        data = _data(seed=19)
        params = {**PARAMS, "fused_block_size": 4, "seed": 3}
        ref = _train(mxu_engine, params, data, 12)
        st = getattr(ref.gbdt, "_pipeline_stats", None)
        assert st is not None and st.blocks >= 1
        d = str(tmp_path)
        # run A stops at 6 so the resume has a pipelined tail to train
        ck = _train(mxu_engine, params, data, 6,
                    callbacks=[cb.checkpoint(6, d)])
        # the checkpoint callback forced the serial loop on run A
        assert getattr(ck.gbdt, "_pipeline_stats", None) is None
        assert ck.current_iteration() == 6
        found = latest_checkpoint(d)
        assert found is not None
        X, y = data
        resumed = mxu_engine.train(
            dict(params),
            lgb.Dataset(X, label=y, params={"max_bin": 31}),
            num_boost_round=12, resume_from=found)
        st = getattr(resumed.gbdt, "_pipeline_stats", None)
        assert st is not None and st.blocks >= 1
        assert resumed.current_iteration() == 12
        assert _strip(resumed.model_to_string()) == \
            _strip(ref.model_to_string())
