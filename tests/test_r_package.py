"""pytest-driven smoke run of the R bridge's testthat suite (reference
R-package/tests/). Skips when no R interpreter (this CI image has
none); run on a machine with R + reticulate to validate the bridge."""

import os
import shutil
import subprocess

import pytest

pytestmark = pytest.mark.slow


def test_r_testthat_suite():
    rscript = shutil.which("Rscript")
    if rscript is None:
        pytest.skip("Rscript not available in this image")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [rscript, os.path.join(repo, "R-package", "tests", "testthat.R")],
        cwd=repo, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout + proc.stderr
