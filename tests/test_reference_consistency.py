"""Differential testing against the reference LightGBM binary.

SURVEY.md §4 test_consistency equivalent, but stronger: the model text
format (tree.py) claims byte-level compatibility with the reference
(gbdt_model_text.cpp), so models must cross-load in BOTH directions:

- ours -> reference: a model trained here is scored by the reference CLI
  and must reproduce our predictions;
- reference -> ours: a model trained by the reference CLI is loaded by
  our Booster and must reproduce the reference's predictions.

Requires the reference CLI binary (build out-of-tree:
`cmake -S /root/reference -B /tmp/lgbbuild && cmake --build /tmp/lgbbuild
--target lightgbm`); tests skip when it is absent. Set LGBM_REFERENCE_BIN
to point at the binary explicitly.
"""

import os
import subprocess

import numpy as np
import pytest

import lightgbm_tpu as lgb

_BIN = os.environ.get("LGBM_REFERENCE_BIN", "/tmp/lgbbuild/lightgbm")

pytestmark = pytest.mark.skipif(
    not os.path.exists(_BIN), reason="reference binary not built")


def _run_ref(conf_path):
    res = subprocess.run([_BIN, f"config={conf_path}"],
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr


def _write_csv(path, X, y=None):
    arr = X if y is None else np.column_stack([y, X])
    np.savetxt(path, arr, delimiter=",", fmt="%.8g")


def _data(seed=0, n=3000, f=6, with_nan=False, with_cat=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    if with_cat:
        X[:, 1] = rng.randint(0, 12, n)
    if with_nan:
        X[rng.rand(n) < 0.05, 2] = np.nan
    y = ((np.nan_to_num(X[:, 0]) + 0.4 * np.nan_to_num(X[:, 2]) +
          (X[:, 1].astype(int) % 3 == 0 if with_cat else 0)) >
         0.3).astype(np.float64)
    return X, y


class TestOursToReference:
    def _check(self, tmp_path, with_nan=False, with_cat=False, **params):
        X, y = _data(1, with_nan=with_nan, with_cat=with_cat)
        cat = [1] if with_cat else None
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbosity": -1, "min_data_in_leaf": 5, **params},
                        lgb.Dataset(X, label=y, categorical_feature=cat),
                        10)
        model = tmp_path / "ours.txt"
        bst.save_model(str(model))
        # the reference CLI defaults label_column=0, so prediction files
        # carry the label in column 0 like training files
        _write_csv(tmp_path / "test.csv", X, y)
        conf = tmp_path / "predict.conf"
        conf.write_text(
            f"task=predict\ndata={tmp_path}/test.csv\n"
            f"input_model={model}\noutput_result={tmp_path}/ref_preds.txt\n"
            "header=false\nlabel_column=0\n")
        _run_ref(conf)
        ref = np.loadtxt(tmp_path / "ref_preds.txt")
        ours = bst.predict(X)
        np.testing.assert_allclose(ref, ours, rtol=1e-5, atol=1e-6)

    def test_numerical(self, tmp_path):
        self._check(tmp_path)

    def test_nan_routing(self, tmp_path):
        self._check(tmp_path, with_nan=True)

    def test_categorical_bitsets(self, tmp_path):
        self._check(tmp_path, with_cat=True)

    def test_linear_trees(self, tmp_path):
        # linear-leaf serialization (is_linear/leaf_const/leaf_coeff)
        # scored by the reference's linear prediction path
        X, y = _data(3)
        yr = (X[:, 0] + 0.5 * X[:, 2]).astype(np.float64)
        bst = lgb.train({"objective": "regression", "num_leaves": 8,
                         "linear_tree": True, "verbosity": -1},
                        lgb.Dataset(X, label=yr), 8)
        model = tmp_path / "lin.txt"
        bst.save_model(str(model))
        _write_csv(tmp_path / "test.csv", X, yr)
        conf = tmp_path / "predict.conf"
        conf.write_text(
            f"task=predict\ndata={tmp_path}/test.csv\n"
            f"input_model={model}\noutput_result={tmp_path}/p.txt\n"
            "header=false\nlabel_column=0\n")
        _run_ref(conf)
        np.testing.assert_allclose(np.loadtxt(tmp_path / "p.txt"),
                                   bst.predict(X), rtol=1e-5, atol=1e-6)


class TestReferenceToOurs:
    def test_cross_load(self, tmp_path):
        X, y = _data(2, with_nan=True)
        _write_csv(tmp_path / "train.csv", X, y)
        train_conf = tmp_path / "train.conf"
        train_conf.write_text(
            f"task=train\nobjective=binary\ndata={tmp_path}/train.csv\n"
            f"output_model={tmp_path}/ref_model.txt\nnum_trees=10\n"
            "num_leaves=15\nmin_data_in_leaf=5\nheader=false\n"
            "label_column=0\nverbosity=-1\n")
        _run_ref(train_conf)
        pred_conf = tmp_path / "pred.conf"
        pred_conf.write_text(
            f"task=predict\ndata={tmp_path}/train.csv\n"
            f"input_model={tmp_path}/ref_model.txt\n"
            f"output_result={tmp_path}/ref_preds.txt\nheader=false\n"
            "label_column=0\n")
        _run_ref(pred_conf)
        ref_preds = np.loadtxt(tmp_path / "ref_preds.txt")
        ours = lgb.Booster(model_file=str(tmp_path / "ref_model.txt"))
        np.testing.assert_allclose(ours.predict(X), ref_preds,
                                   rtol=1e-5, atol=1e-6)

    def test_cross_load_categorical(self, tmp_path):
        X, y = _data(4, with_cat=True)
        _write_csv(tmp_path / "train.csv", X, y)
        train_conf = tmp_path / "train.conf"
        train_conf.write_text(
            f"task=train\nobjective=binary\ndata={tmp_path}/train.csv\n"
            f"output_model={tmp_path}/ref_model.txt\nnum_trees=10\n"
            "num_leaves=15\nmin_data_in_leaf=5\nheader=false\n"
            "label_column=0\ncategorical_feature=1\nverbosity=-1\n")
        _run_ref(train_conf)
        pred_conf = tmp_path / "pred.conf"
        pred_conf.write_text(
            f"task=predict\ndata={tmp_path}/train.csv\n"
            f"input_model={tmp_path}/ref_model.txt\n"
            f"output_result={tmp_path}/ref_preds.txt\nheader=false\n"
            "label_column=0\n")
        _run_ref(pred_conf)
        ref_preds = np.loadtxt(tmp_path / "ref_preds.txt")
        ours = lgb.Booster(model_file=str(tmp_path / "ref_model.txt"))
        np.testing.assert_allclose(ours.predict(X), ref_preds,
                                   rtol=1e-5, atol=1e-6)


class TestTrainingParity:
    """Same-data training parity: both sides train the same config and
    must reach comparable loss at equal tree count — the class of check
    that catches objective-formulation bugs (round 5 caught a multiclass
    softmax hessian factor of 2 where the reference uses k/(k-1),
    multiclass_objective.hpp:31)."""

    def test_multiclass_loss_parity(self, tmp_path):
        rng = np.random.RandomState(6)
        n, k = 4000, 4
        X = rng.randn(n, 6)
        centers = np.random.RandomState(7).randn(k, 4) * 1.2
        d = ((X[:, None, :4] - centers[None]) ** 2).sum(-1)
        d += 1.2 * rng.gumbel(size=(n, k))
        y = np.argmin(d, axis=1).astype(np.float64)
        _write_csv(tmp_path / "train.csv", X, y)
        conf = tmp_path / "train.conf"
        conf.write_text(
            f"task=train\nobjective=multiclass\nnum_class={k}\n"
            f"data={tmp_path}/train.csv\n"
            f"output_model={tmp_path}/ref_model.txt\nnum_trees=30\n"
            "num_leaves=15\nmin_data_in_leaf=5\nheader=false\n"
            "label_column=0\nverbosity=-1\n")
        _run_ref(conf)
        pred_conf = tmp_path / "pred.conf"
        pred_conf.write_text(
            f"task=predict\ndata={tmp_path}/train.csv\n"
            f"input_model={tmp_path}/ref_model.txt\n"
            f"output_result={tmp_path}/ref_preds.txt\nheader=false\n"
            "label_column=0\npredict_raw_score=true\n")
        _run_ref(pred_conf)
        ref_raw = np.loadtxt(tmp_path / "ref_preds.txt").reshape(-1, k)

        bst = lgb.train({"objective": "multiclass", "num_class": k,
                         "num_leaves": 15, "min_data_in_leaf": 5,
                         "verbosity": -1},
                        lgb.Dataset(X, label=y), 30)
        ours_raw = np.asarray(bst.predict(X, raw_score=True)).reshape(-1, k)

        def mll(raw):
            p = raw - raw.max(axis=1, keepdims=True)
            logp = p - np.log(np.exp(p).sum(axis=1, keepdims=True))
            return -np.mean(logp[np.arange(n), y.astype(int)])

        ours, ref = mll(ours_raw), mll(ref_raw)
        # same objective/shape/count: training losses must track.
        # Small shapes carry growth-order noise (binary measures ~2.6%
        # at this exact shape; multiclass compounds it over k trees per
        # iteration) — the threshold is set to pass that noise while
        # failing formula-scale bugs (the factor-2 hessian bug this
        # test was written against measured ~25%)
        assert abs(ours - ref) / ref < 0.12, (ours, ref)
