"""Bench regression sentinel (observability/regress.py + bench.py
--compare) — tier-1. Two halves: (1) the REAL trajectory in the repo
root must schema-validate and carry no regressions (the contract that
makes the sentinel a guard for every later round); (2) synthetic
trajectories prove the detectors fire: a >10% drop, a broken latest
record, a multichip flip, and the --strict exit code."""

import json
import os
import subprocess
import sys

import pytest

from lightgbm_tpu.observability import regress

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
BENCH = os.path.join(REPO, "bench.py")


def _write(dirpath, name, rec):
    with open(os.path.join(str(dirpath), name), "w") as fh:
        json.dump(rec, fh)


def _bench_rec(value, rc=0, metric="higgs1m_trees_per_sec", **extra):
    parsed = None if value is None else {
        "metric": metric, "unit": "trees/s", "value": value, **extra}
    return {"n": 1, "cmd": "python bench.py", "rc": rc, "tail": "",
            "parsed": parsed}


# ---------------------------------------------------------------------------
# the real trajectory is a checked artifact

def test_real_trajectory_schema_validates():
    traj = regress.load_trajectory(REPO)
    assert traj["bench"], "no BENCH_r*.json in the repo root"
    assert traj["serve"], "no SERVE_r*.json in the repo root"
    problems = []
    for kind in ("bench", "multichip", "serve"):
        for _, name, rec in traj[kind]:
            problems += regress.validate_record(kind, name, rec)
    assert not problems, "\n".join(problems)


def test_real_trajectory_has_no_regressions():
    result = regress.compare()
    assert result["root"] == REPO
    assert result["regressions"] == [], regress.render_compare(result)
    # the headline metrics are tracked with best-so-far context
    assert "higgs1m_trees_per_sec" in result["metrics"]
    assert "serve:serve_sustained_qps_p99lt10ms" in result["metrics"]


def test_real_serve_record_holds_the_slo():
    """The committed SERVE_r*.json must be a usable sample: rc==0, a
    positive sustained QPS, p99 under the 10ms SLO, and zero drops in
    every stage (the bench_serve.py contract the sentinel guards)."""
    (_, name, rec) = regress.load_trajectory(REPO)["serve"][-1]
    assert regress.validate_record("serve", name, rec) == []
    assert rec["rc"] == 0
    parsed = rec["parsed"]
    assert parsed["metric"] == "serve_sustained_qps_p99lt10ms"
    assert parsed["unit"] == "qps" and parsed["value"] > 0
    assert parsed["slo_held"] is True and parsed["p99_ms"] < 10.0
    assert all(s["dropped"] == 0 for s in parsed["stages"])


# ---------------------------------------------------------------------------
# detectors, on synthetic trajectories

def test_drop_beyond_threshold_is_flagged(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _bench_rec(2.0))
    _write(tmp_path, "BENCH_r02.json", _bench_rec(3.0))
    _write(tmp_path, "BENCH_r03.json", _bench_rec(2.5))   # -16.7% vs 3.0
    result = regress.compare(str(tmp_path))
    (reg,) = result["regressions"]
    assert reg["metric"] == "higgs1m_trees_per_sec"
    assert reg["best"] == 3.0 and reg["best_round"] == 2
    assert reg["drop_frac"] == pytest.approx(1 - 2.5 / 3.0, abs=1e-4)
    assert "REGRESSION" in regress.render_compare(result)


def test_drop_within_threshold_is_quiet(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _bench_rec(3.0))
    _write(tmp_path, "BENCH_r02.json", _bench_rec(2.75))  # -8.3%: ok
    result = regress.compare(str(tmp_path))
    assert result["regressions"] == []
    assert result["metrics"]["higgs1m_trees_per_sec"]["delta_frac"] < 0


def test_ratio_side_channels_tracked(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _bench_rec(2.0, vs_baseline=0.8))
    _write(tmp_path, "BENCH_r02.json", _bench_rec(2.1, vs_baseline=0.5))
    result = regress.compare(str(tmp_path))
    (reg,) = result["regressions"]
    assert reg["metric"] == "higgs1m_trees_per_sec:vs_baseline"


def test_unusable_rounds_excluded_from_best(tmp_path):
    # an rc!=0 round and a value<=0 round never become the best bar
    _write(tmp_path, "BENCH_r01.json", _bench_rec(2.0))
    _write(tmp_path, "BENCH_r02.json", _bench_rec(99.0, rc=1))
    _write(tmp_path, "BENCH_r03.json", _bench_rec(0.0))
    _write(tmp_path, "BENCH_r04.json", _bench_rec(2.1))
    result = regress.compare(str(tmp_path))
    assert result["regressions"] == []
    entry = result["metrics"]["higgs1m_trees_per_sec"]
    assert entry["best"] == 2.0 and entry["samples"] == 2


def test_broken_latest_record_is_a_regression(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _bench_rec(2.0))
    _write(tmp_path, "BENCH_r02.json", _bench_rec(None, rc=3))
    result = regress.compare(str(tmp_path))
    (reg,) = result["regressions"]
    assert reg["metric"] == "bench_record"
    assert reg["record"] == "BENCH_r02.json"


def test_skipped_latest_round_is_declared_not_broken(tmp_path):
    # a record carrying skipped=true + a reason (hardware denial, r06
    # protocol) is not a sample and does not trip the unusable-latest
    # rule — unlike an rc=0/value=0 record, which does
    _write(tmp_path, "BENCH_r01.json", _bench_rec(2.0))
    _write(tmp_path, "BENCH_r02.json",
           {**_bench_rec(None), "skipped": True,
            "skip_reason": "device probe timed out"})
    result = regress.compare(str(tmp_path))
    assert result["regressions"] == []
    entry = result["metrics"]["higgs1m_trees_per_sec"]
    assert entry["latest_round"] == 1 and entry["samples"] == 1


def test_skipped_record_requires_a_reason(tmp_path):
    rec = {**_bench_rec(None), "skipped": True}
    problems = regress.validate_record("bench", "BENCH_r09.json", rec)
    assert any("skip_reason" in p for p in problems)
    rec["skip_reason"] = "wedged accelerator tunnel"
    assert regress.validate_record("bench", "BENCH_r09.json", rec) == []


def test_serve_series_regressions_flagged(tmp_path):
    """SERVE_r*.json rides the bench schema: a QPS drop beyond the
    threshold and a broken latest serve round both fire, under the
    'serve:' metric namespace."""
    rec = lambda v, rc=0: _bench_rec(v, rc=rc,
                                     metric="serve_sustained_qps_p99lt10ms")
    _write(tmp_path, "SERVE_r01.json", rec(800.0))
    _write(tmp_path, "SERVE_r02.json", rec(500.0))       # -37.5%
    result = regress.compare(str(tmp_path))
    (reg,) = result["regressions"]
    assert reg["metric"] == "serve:serve_sustained_qps_p99lt10ms"
    assert reg["best"] == 800.0
    # a crashed latest serve bench is itself a regression
    _write(tmp_path, "SERVE_r03.json", rec(None, rc=1))
    result = regress.compare(str(tmp_path))
    assert {r["metric"] for r in result["regressions"]} == {
        "serve:serve_sustained_qps_p99lt10ms", "serve_record"}
    assert result["serve_records"] == 3


def test_multimodel_packed_qps_drop_flagged(tmp_path):
    """The serve record's packed multi-model QPS column is its own
    tracked series: a >10% drop in mm_packed_qps fires even when the
    headline single-model QPS holds steady."""
    rec = lambda mm: _bench_rec(800.0, mm_packed_qps=mm,
                                metric="serve_sustained_qps_p99lt10ms")
    _write(tmp_path, "SERVE_r01.json", rec(400.0))
    _write(tmp_path, "SERVE_r02.json", rec(250.0))       # -37.5%
    result = regress.compare(str(tmp_path))
    (reg,) = result["regressions"]
    assert reg["metric"] == \
        "serve:serve_sustained_qps_p99lt10ms:mm_packed_qps"
    assert reg["best"] == 400.0


def test_multimodel_speedup_within_threshold_quiet(tmp_path):
    """mm_packed_speedup is tracked alongside mm_packed_qps but small
    wobble stays quiet; rounds without the multi-model stage simply
    contribute no sample (no false regression from a missing column)."""
    _write(tmp_path, "SERVE_r01.json",
           _bench_rec(800.0, mm_packed_qps=400.0, mm_packed_speedup=1.5,
                      metric="serve_sustained_qps_p99lt10ms"))
    _write(tmp_path, "SERVE_r02.json",      # no mm stage this round
           _bench_rec(810.0, metric="serve_sustained_qps_p99lt10ms"))
    _write(tmp_path, "SERVE_r03.json",
           _bench_rec(805.0, mm_packed_qps=390.0, mm_packed_speedup=1.45,
                      metric="serve_sustained_qps_p99lt10ms"))
    result = regress.compare(str(tmp_path))
    assert result["regressions"] == []
    spd = result["metrics"][
        "serve:serve_sustained_qps_p99lt10ms:mm_packed_speedup"]
    assert spd["samples"] == 2 and spd["latest"] == 1.45


def test_multichip_flip_is_a_regression(tmp_path):
    mc = {"n_devices": 2, "rc": 0, "ok": True, "skipped": False}
    _write(tmp_path, "MULTICHIP_r01.json", mc)
    _write(tmp_path, "MULTICHIP_r02.json",
           {**mc, "rc": 1, "ok": False})
    result = regress.compare(str(tmp_path))
    (reg,) = result["regressions"]
    assert reg["metric"] == "multichip_ok"
    # skipped rounds are not samples
    _write(tmp_path, "MULTICHIP_r03.json", {**mc, "skipped": True})
    assert regress.compare(str(tmp_path))["metrics"]["multichip_ok"][
        "samples"] == 2


def test_multichip_throughput_drop_is_a_regression(tmp_path):
    """r06+ MULTICHIP records carry real training throughput
    (trees_per_sec / vs_baseline from the 8-device run); a >10% drop
    vs best-so-far fires like any other tracked series, while legacy
    dry-run records (no throughput fields) stay schema-valid and
    contribute no samples."""
    mc = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
          "tree_learner": "data"}
    _write(tmp_path, "MULTICHIP_r01.json", mc)    # legacy dry-run round
    _write(tmp_path, "MULTICHIP_r02.json",
           {**mc, "trees_per_sec": 40.0, "vs_baseline": 0.31})
    _write(tmp_path, "MULTICHIP_r03.json",
           {**mc, "trees_per_sec": 30.0, "vs_baseline": 0.23})  # -25%
    for _, name, rec in regress.load_trajectory(
            str(tmp_path))["multichip"]:
        assert regress.validate_record("multichip", name, rec) == []
    result = regress.compare(str(tmp_path))
    metrics = {r["metric"] for r in result["regressions"]}
    assert "multichip_trees_per_sec" in metrics
    assert "multichip_vs_baseline" in metrics
    entry = result["metrics"]["multichip_trees_per_sec"]
    assert entry["best"] == 40.0 and entry["samples"] == 2


def test_multichip_throughput_within_threshold_is_quiet(tmp_path):
    mc = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
          "tree_learner": "data"}
    _write(tmp_path, "MULTICHIP_r01.json",
           {**mc, "trees_per_sec": 40.0, "vs_baseline": 0.31})
    _write(tmp_path, "MULTICHIP_r02.json",
           {**mc, "trees_per_sec": 38.0, "vs_baseline": 0.29})  # -5%
    result = regress.compare(str(tmp_path))
    assert result["regressions"] == []
    assert result["metrics"]["multichip_trees_per_sec"]["best"] == 40.0


# ---------------------------------------------------------------------------
# bench.py --compare wiring (subprocess: the real CLI path)

def _run_compare(*argv):
    return subprocess.run(
        [sys.executable, BENCH, "--compare", *argv],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120)


def test_bench_compare_real_trajectory_passes():
    proc = _run_compare("--strict")
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["bench_regressions"]["regressions"] == []
    assert "no regressions" in proc.stderr


def test_bench_compare_strict_fails_on_regression(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _bench_rec(3.0))
    _write(tmp_path, "BENCH_r02.json", _bench_rec(1.0))
    proc = _run_compare("--strict", "--trajectory-dir", str(tmp_path))
    assert proc.returncode == 1
    assert "REGRESSION" in proc.stderr
    # without --strict the same trajectory reports but exits 0
    proc = _run_compare("--trajectory-dir", str(tmp_path))
    assert proc.returncode == 0
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["bench_regressions"]["regressions"]
