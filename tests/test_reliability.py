"""Reliability subsystem: fault registry, retry, guards, drain paths.

Every registered fault site gets an injection test that completes
correctly with the event visible in the reliability counters (the
ISSUE acceptance bar). The mxu fused path itself cannot compile on
this jax build (see test_bench_robustness at seed), so fused_dispatch
is exercised at the registry/shim boundary that train_many calls.
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import callback as cb
from lightgbm_tpu.reliability import (InjectedFault, counters, faults,
                                      guards, retry_call)
from lightgbm_tpu.reliability.faults import parse_schedule
from conftest import make_binary

PARAMS = {"objective": "binary", "num_leaves": 7, "learning_rate": 0.2,
          "max_bin": 31, "verbosity": -1, "min_data_in_leaf": 5}


def _ds(n=300, f=5, seed=2):
    X, y = make_binary(n=n, f=f, seed=seed)
    return X, y, lgb.Dataset(X, label=y, params={"max_bin": 31})


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    counters.reset()
    yield
    faults.clear()
    counters.reset()
    os.environ.pop("LGBM_TPU_INJECT_FUSED_FAULT", None)


# ----------------------------------------------------------------------
# registry semantics
class TestFaultRegistry:
    def test_parse_schedule(self):
        assert parse_schedule("2") == (0, 2)
        assert parse_schedule("3:1") == (3, 1)
        assert parse_schedule("0") == (0, 0)
        with pytest.raises(ValueError):
            parse_schedule("nope")

    def test_skip_then_fail(self):
        faults.schedule("histogram_build", fail=2, skip=1)
        faults.inject("histogram_build")  # skipped
        with pytest.raises(InjectedFault):
            faults.inject("histogram_build")
        with pytest.raises(InjectedFault):
            faults.inject("histogram_build")
        faults.inject("histogram_build")  # schedule exhausted
        assert faults.trips("histogram_build") == 2
        assert faults.calls("histogram_build") == 4
        assert faults.remaining("histogram_build") == (0, 0)

    def test_injected_context_manager(self):
        with faults.injected("collective_psum", fail=1):
            with pytest.raises(InjectedFault):
                faults.inject("collective_psum")
        # cleared on exit even when unconsumed
        with faults.injected("collective_psum", fail=5):
            pass
        faults.inject("collective_psum")

    def test_env_seeding_never_mutates_environ(self):
        os.environ["LGBM_TPU_INJECT_FUSED_FAULT"] = "1:1"
        site = "fused_dispatch"
        faults.schedule_from_env(site, "LGBM_TPU_INJECT_FUSED_FAULT")
        faults.inject(site)  # skip
        with pytest.raises(InjectedFault):
            faults.inject(site)
        # re-reading the same env value must NOT re-seed the schedule
        faults.schedule_from_env(site, "LGBM_TPU_INJECT_FUSED_FAULT")
        faults.inject(site)
        assert os.environ["LGBM_TPU_INJECT_FUSED_FAULT"] == "1:1"
        # a *changed* value re-seeds
        os.environ["LGBM_TPU_INJECT_FUSED_FAULT"] = "1"
        faults.schedule_from_env(site, "LGBM_TPU_INJECT_FUSED_FAULT")
        with pytest.raises(InjectedFault):
            faults.inject(site)

    def test_snapshot_counts_trips(self):
        faults.schedule("serving_device_predict", fail=1)
        with pytest.raises(InjectedFault):
            faults.inject("serving_device_predict")
        assert faults.snapshot() == {"serving_device_predict": 1}


# ----------------------------------------------------------------------
# retry helper
class TestRetry:
    def test_recovers_and_counts(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("transient")
            return "ok"

        delays = []
        assert retry_call(flaky, attempts=3, backoff_ms=10.0,
                          sleep=delays.append) == "ok"
        assert counters.get("device_retries") == 2
        assert delays == [0.01, 0.02]

    def test_backoff_capped(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 5:
                raise RuntimeError("x")
            return 1

        delays = []
        retry_call(flaky, attempts=5, backoff_ms=100.0,
                   backoff_max_ms=150.0, sleep=delays.append)
        assert delays == [0.1, 0.15, 0.15, 0.15]

    def test_exhaustion_propagates(self):
        def dead():
            raise ValueError("permanent")

        with pytest.raises(ValueError):
            retry_call(dead, attempts=2, backoff_ms=0.0, sleep=lambda s: None)
        assert counters.get("device_retries") == 1

    def test_on_retry_callback(self):
        seen = []

        def flaky():
            if not seen:
                seen.append(1)
                raise RuntimeError("once")
            return True

        assert retry_call(flaky, attempts=2, backoff_ms=0.0,
                          on_retry=lambda: seen.append("cb"),
                          sleep=lambda s: None)
        assert "cb" in seen


# ----------------------------------------------------------------------
# per-site injection (the acceptance bar: each site completes correctly
# with the event visible in counters)
@pytest.mark.faults
class TestFaultSites:
    def test_histogram_build_retry_recovers(self):
        X, y, ds = _ds()
        faults.schedule("histogram_build", fail=1)
        bst = lgb.train(dict(PARAMS), ds, num_boost_round=3)
        assert bst.current_iteration() == 3
        assert faults.trips("histogram_build") == 1
        assert counters.get("device_retries") == 1
        # the retried model is identical to an unfaulted one
        ref = lgb.train(dict(PARAMS), _ds()[2], num_boost_round=3)
        assert bst.model_to_string() == ref.model_to_string()

    def test_histogram_build_exhaustion_raises(self):
        X, y, ds = _ds()
        faults.schedule("histogram_build", fail=10)
        p = dict(PARAMS, retry_max_attempts=2)
        with pytest.raises(InjectedFault):
            lgb.train(p, ds, num_boost_round=2)
        assert counters.get("device_retries") >= 1

    def test_collective_psum_site(self):
        from lightgbm_tpu.parallel.comm import check_collective_fault
        check_collective_fault()  # no schedule -> no-op
        faults.schedule("collective_psum", fail=1)
        with pytest.raises(InjectedFault):
            check_collective_fault()
        assert faults.trips("collective_psum") == 1
        check_collective_fault()  # consumed

    def test_collective_psum_end_to_end(self):
        try:
            from jax import shard_map  # noqa: F401
        except ImportError:
            pytest.skip("jax.shard_map unavailable on this jax build")
        X, y, ds = _ds(n=400)
        faults.schedule("collective_psum", fail=1)
        bst = lgb.train(dict(PARAMS, tree_learner="data", num_devices=4),
                        ds, num_boost_round=3)
        assert bst.current_iteration() == 3
        assert faults.trips("collective_psum") == 1
        assert counters.get("device_retries") == 1

    def test_checkpoint_io_failure_does_not_kill_training(self, tmp_path):
        X, y, ds = _ds()
        faults.schedule("checkpoint_io", fail=1)
        bst = lgb.train(dict(PARAMS), ds, num_boost_round=4,
                        callbacks=[cb.checkpoint(2, str(tmp_path))])
        assert bst.current_iteration() == 4
        assert counters.get("checkpoint_failures") == 1
        assert counters.get("checkpoint_saves") == 1  # iteration-4 save
        bundles = [p for p in os.listdir(tmp_path) if p.startswith("ckpt_")]
        assert bundles == ["ckpt_0000004"]

    def test_serving_device_predict_retry_recovers(self):
        X, y, ds = _ds()
        bst = lgb.train(dict(PARAMS), ds, num_boost_round=3)
        from lightgbm_tpu.serving import Server
        with Server(max_wait_ms=0.5, retry_attempts=3,
                    retry_backoff_ms=1.0) as srv:
            srv.load_model("m", booster=bst)
            faults.schedule("serving_device_predict", fail=1)
            out = srv.predict("m", X[:8])
            snap = srv.metrics_snapshot("m")["models"]["m"]
        np.testing.assert_allclose(out, bst.predict(X[:8]), rtol=1e-5)
        assert snap["device_retries"] == 1
        assert snap["fallbacks"] == 0
        assert not snap["degraded"]

    def test_serving_device_predict_exhaustion_falls_back(self):
        X, y, ds = _ds()
        bst = lgb.train(dict(PARAMS), ds, num_boost_round=3)
        from lightgbm_tpu.serving import Server
        # breaker_threshold=1: one exhausted dispatch opens the sole
        # replica's breaker, so `degraded` (now a derived breaker
        # property, not a sticky flag) reads True until the cooldown
        # probe heals it (docs/Serving.md "Degradation ladder")
        with Server(max_wait_ms=0.5, retry_attempts=2,
                    retry_backoff_ms=1.0, breaker_threshold=1,
                    breaker_cooldown_ms=60000.0) as srv:
            srv.load_model("m", booster=bst)
            faults.schedule("serving_device_predict", fail=10)
            out = srv.predict("m", X[:8])
            snap = srv.metrics_snapshot("m")["models"]["m"]
        np.testing.assert_allclose(out, bst.predict(X[:8]), rtol=1e-6)
        assert snap["degraded"]
        assert snap["replicas"][0]["state"] == "open"
        assert snap["fallbacks"] == 1
        assert counters.get("fallbacks") == 1

    def test_fused_dispatch_env_shim(self):
        # legacy contract: env var seeds the schedule, is never mutated
        from lightgbm_tpu.boosting.gbdt import _maybe_inject_fused_fault
        os.environ["LGBM_TPU_INJECT_FUSED_FAULT"] = "1"
        with pytest.raises(InjectedFault):
            _maybe_inject_fused_fault()
        _maybe_inject_fused_fault()  # consumed
        assert os.environ["LGBM_TPU_INJECT_FUSED_FAULT"] == "1"
        assert faults.trips("fused_dispatch") == 1
        assert faults.remaining("fused_dispatch") == (0, 0)


# ----------------------------------------------------------------------
# guard rails
def _nan_fobj_factory(bad_call):
    def fobj(preds, dataset):
        lbl = np.asarray(dataset.get_label())
        g = np.asarray(preds) - lbl
        h = np.ones_like(g)
        fobj.calls += 1
        if fobj.calls == bad_call:
            g = g.copy()
            g[0] = np.nan
        return g, h
    fobj.calls = 0
    return fobj


@pytest.mark.faults
class TestGuards:
    def test_all_finite(self):
        import jax.numpy as jnp
        a = jnp.ones(4)
        assert guards.all_finite(a, a)
        assert guards.all_finite(None, a)
        assert not guards.all_finite(a.at[1].set(jnp.inf))

    @pytest.mark.parametrize("policy", ["warn", "skip_iteration",
                                        "rollback"])
    def test_nonfatal_policies_complete(self, policy):
        X, y, ds = _ds()
        p = dict(PARAMS, guard_nonfinite=policy)
        bst = lgb.train(p, ds, num_boost_round=5,
                        fobj=_nan_fobj_factory(3))
        assert bst.current_iteration() == 5
        assert counters.get("guard_trips") == 1
        assert np.all(np.isfinite(bst.predict(X)))

    def test_raise_policy(self):
        X, y, ds = _ds()
        p = dict(PARAMS, guard_nonfinite="raise")
        with pytest.raises(guards.GuardError):
            lgb.train(p, ds, num_boost_round=5, fobj=_nan_fobj_factory(3))
        assert counters.get("guard_trips") == 1

    def test_clean_run_never_trips(self):
        X, y, ds = _ds()
        p = dict(PARAMS, guard_nonfinite="warn")
        bst = lgb.train(p, ds, num_boost_round=5)
        assert counters.get("guard_trips") == 0
        # guard must be a pure observer on a healthy run: identical trees
        ref = lgb.train(dict(PARAMS), _ds()[2], num_boost_round=5)
        tree_part = bst.model_to_string().split("end of parameters")[1]
        ref_part = ref.model_to_string().split("end of parameters")[1]
        assert tree_part == ref_part

    def test_invalid_policy_rejected(self):
        X, y, ds = _ds()
        with pytest.raises(Exception):
            lgb.train(dict(PARAMS, guard_nonfinite="explode"), ds,
                      num_boost_round=1)


# ----------------------------------------------------------------------
# batcher shutdown drain (satellite 2)
class TestBatcherDrain:
    def test_close_drains_queue_through_worker(self):
        X, y, ds = _ds()
        bst = lgb.train(dict(PARAMS), ds, num_boost_round=3)
        from lightgbm_tpu.serving import Server
        srv = Server(max_wait_ms=200.0)
        srv.load_model("m", booster=bst)
        b = srv.batcher("m")
        b.pause()
        futs = [srv.predict_async("m", X[i:i + 4]) for i in range(0, 12, 4)]
        assert b.queue_depth() == 3
        b.resume()
        srv.close()  # worker drains the queue before exiting
        res = np.concatenate([f.result(timeout=10) for f in futs])
        np.testing.assert_allclose(res, bst.predict(X[:12]), rtol=1e-5)

    def test_wedged_close_resolves_via_host_fallback(self):
        from lightgbm_tpu.serving.batcher import BatcherClosed
        X, y, ds = _ds()
        bst = lgb.train(dict(PARAMS), ds, num_boost_round=3)
        from lightgbm_tpu.serving import Server
        srv = Server(max_wait_ms=500.0)
        srv.load_model("m", booster=bst)
        b = srv.batcher("m")
        b.pause()
        futs = [srv.predict_async("m", X[i:i + 4]) for i in range(0, 12, 4)]
        # simulate a wedged worker: close() cannot join, leftovers get
        # BatcherClosed and the server re-routes them to host predict
        b._worker.join = lambda timeout=None: None
        b.close()
        res = np.concatenate([f.result(timeout=10) for f in futs])
        snap = srv.metrics_snapshot("m")["models"]["m"]
        np.testing.assert_allclose(res, bst.predict(X[:12]), rtol=1e-6)
        assert not snap["degraded"]          # model itself is healthy
        assert snap["fallbacks"] == 3
        assert snap["errors"] == 0
        with pytest.raises(RuntimeError):
            b.submit(np.zeros((1, 5), np.int32))

    def test_metrics_snapshot_schema(self):
        X, y, ds = _ds()
        bst = lgb.train(dict(PARAMS), ds, num_boost_round=2)
        from lightgbm_tpu.serving import Server
        with Server() as srv:
            srv.load_model("m", booster=bst)
            srv.predict("m", X[:4])
            snap = srv.metrics_snapshot("m")["models"]["m"]
        for key in ("device_retries", "fallbacks", "guard_trips"):
            assert key in snap, key


# ----------------------------------------------------------------------
# process-wide counters
class TestCounters:
    def test_snapshot_schema_complete(self):
        snap = counters.snapshot()
        for key in ("device_retries", "fallbacks", "guard_trips",
                    "checkpoint_saves", "checkpoint_failures"):
            assert key in snap and snap[key] == 0

    def test_inc_and_reset(self):
        counters.inc("guard_trips")
        counters.inc("guard_trips", 2)
        assert counters.get("guard_trips") == 3
        counters.reset()
        assert counters.get("guard_trips") == 0
