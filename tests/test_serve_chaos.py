"""Serving chaos + load tests (docs/Serving.md "Degradation ladder").

Sustained concurrent load from the `testing.chaos_serve` harness while
the fault registry kills replica dispatches, a breaker is forced open,
and the model is hot-swapped mid-run. The ledger then proves the
ISSUE-11 acceptance criteria exactly:

- zero requests dropped or left hanging (every issued request gets a
  definitive outcome);
- every answer bit-identical to a host predict of the same rows
  (dyadic boosters make f32 device sums == f64 host sums, so a torn
  model or corrupted batch slice cannot hide inside a tolerance);
- the breaker observed opening, half-open probing, and re-closing via
  the metrics snapshot alone.

The fast subset here is tier-1; the full open-loop QPS ramp is marked
`slow` and runs via `make serve-chaos`.
"""

import time

import numpy as np
import pytest

from lightgbm_tpu.reliability import InjectedFault, faults
from lightgbm_tpu.serving import Server
from lightgbm_tpu.testing.chaos_serve import (dyadic_booster,
                                              heavy_tailed_sizes,
                                              run_closed_loop,
                                              run_open_loop,
                                              verify_bit_identical)

pytestmark = pytest.mark.serve_chaos


@pytest.fixture(scope="module")
def dyadic():
    return dyadic_booster(seed=3)


@pytest.fixture(scope="module")
def dyadic_v2():
    return dyadic_booster(seed=11)


def test_dyadic_booster_is_bit_exact_on_device(dyadic):
    bst, X = dyadic
    with Server(min_bucket=4, max_bucket=256) as srv:
        srv.load_model("m", booster=bst)
        got = srv.predict("m", X[:200], raw_score=True)
    assert np.array_equal(got, bst.predict(X[:200], raw_score=True))


def test_heavy_tailed_sizes_shape():
    rng = np.random.RandomState(0)
    sizes = heavy_tailed_sizes(rng, 5000, max_rows=64)
    assert sizes.min() >= 1 and sizes.max() <= 64
    # genuinely heavy-tailed: most requests tiny, some near the cap
    assert np.median(sizes) <= 8 and sizes.max() >= 32


def test_chaos_closed_loop_faults_breaker_and_hot_swap(dyadic,
                                                      dyadic_v2):
    """The acceptance scenario: concurrent load + injected device
    faults + forced breaker open + mid-run hot-swap. Zero drops, bit
    identity, breaker trip/heal all observed from metrics."""
    bst, X = dyadic
    bst2, _ = dyadic_v2
    faults.clear()
    with Server(min_bucket=4, max_bucket=256, n_replicas=2,
                retry_attempts=1, breaker_threshold=2,
                breaker_cooldown_ms=50.0, max_queue=512,
                slo_ms=30000.0) as srv:
        srv.load_model("m", booster=bst)

        def _chaos(_i):
            # rung 2-3: injected device failures on replica dispatch —
            # enough consecutive ones to trip a breaker naturally
            faults.schedule("serving_replica_predict", fail=3)
            # hot-swap under live traffic (fresh replicas + breakers;
            # queued requests drain via the old entry's host path)
            srv.hot_swap("m", booster=bst2)
            # rung 4-5: force the new entry's replica 0 open so
            # failover routes everything to replica 1 for a while
            srv.replicas("m").replicas()[0].breaker.force_open()

        res = run_closed_loop(srv, "m", X, n_requests=160, workers=6,
                              max_rows=48, raw_score=True,
                              timeout_s=60.0, seed=1, mid_run=_chaos)

        # --- zero dropped / hanging requests, exact accounting
        assert res.dropped == 0, res.by_outcome()
        outcomes = res.by_outcome()
        assert set(outcomes) <= {"ok", "shed", "deadline"}, outcomes
        assert outcomes.get("ok", 0) >= 150   # sheds are rare at 512 cap

        # --- bit identity: every answer equals host predict of the
        # same rows under the OLD or NEW model (never a torn mixture)
        mismatched = 0
        for rec in res.ok_records():
            ref_old = bst.predict(X[rec.lo:rec.hi], raw_score=True)
            ref_new = bst2.predict(X[rec.lo:rec.hi], raw_score=True)
            val = np.asarray(rec.value)
            if not (np.array_equal(val, ref_old) or
                    np.array_equal(val, ref_new)):
                mismatched += 1
        assert mismatched == 0

        # --- fault sites actually fired and the ladder absorbed them
        assert faults.trips("serving_replica_predict") >= 1
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["version"] == 2

        # --- breaker trip observed in metrics (force_open + injected
        # failures), and it self-heals: after the cooldown, traffic
        # probes the open replica and closes it again
        reps = {r["replica"]: r for r in snap["replicas"]}
        assert reps[0]["opens"] >= 1
        time.sleep(0.1)                    # cooldown (50ms) elapses
        for i in range(12):
            srv.predict("m", X[i:i + 4], raw_score=True)
        snap = srv.metrics_snapshot("m")["models"]["m"]
        reps = {r["replica"]: r for r in snap["replicas"]}
        assert reps[0]["state"] == "closed"
        assert reps[0]["probes"] >= 1 and reps[0]["closes"] >= 1
        assert snap["degraded"] is False
    faults.clear()


def test_chaos_every_replica_open_host_answers(dyadic):
    """Bottom rung: with every breaker open and cooldowns pending, the
    host path answers everything — still bit-identical, still zero
    drops."""
    bst, X = dyadic
    faults.clear()
    with Server(min_bucket=4, max_bucket=256, n_replicas=2,
                retry_attempts=1, breaker_threshold=1,
                breaker_cooldown_ms=60000.0, max_queue=512) as srv:
        srv.load_model("m", booster=bst)
        for rep in srv.replicas("m").replicas():
            rep.breaker.force_open()
        assert srv.metrics_snapshot("m")["models"]["m"]["degraded"] \
            is True
        res = run_closed_loop(srv, "m", X, n_requests=40, workers=4,
                              max_rows=32, raw_score=True, seed=2)
        assert res.dropped == 0
        assert verify_bit_identical(res, bst, X) == len(res.ok_records())
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["fallback_count"] >= len(res.ok_records())


def test_hot_swap_fault_leaves_old_model_serving(dyadic, dyadic_v2):
    """A fault at the `serving_hot_swap` site fires before the
    replacement entry is built: the swap raises, the old model keeps
    serving bit-identically at its old version."""
    bst, X = dyadic
    bst2, _ = dyadic_v2
    with Server(min_bucket=4, max_bucket=256) as srv:
        srv.load_model("m", booster=bst)
        with faults.injected("serving_hot_swap", fail=1):
            with pytest.raises(InjectedFault):
                srv.hot_swap("m", booster=bst2)
        assert faults.trips("serving_hot_swap") >= 1
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["version"] == 1 and snap["swap_drains"] == 0
        got = srv.predict("m", X[:50], raw_score=True)
        assert np.array_equal(got, bst.predict(X[:50], raw_score=True))


def test_deadline_misses_under_pressure(dyadic):
    """A hopeless SLO forces admission sheds; policy 'fallback' still
    answers every request via host — deadline_misses and zero drops."""
    bst, X = dyadic
    with Server(min_bucket=4, max_bucket=256, slo_ms=0.001,
                deadline_policy="fallback", max_queue=512) as srv:
        srv.load_model("m", booster=bst)
        srv.batcher("m").pause()          # queue wait projection blows
        srv.predict_async("m", X[:4], raw_score=True)   # seeds queue
        res = run_closed_loop(srv, "m", X, n_requests=30, workers=3,
                              max_rows=16, raw_score=True, seed=3)
        assert res.dropped == 0
        assert verify_bit_identical(res, bst, X) == len(res.ok_records())
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["deadline_misses"] >= 1
        srv.batcher("m").resume()


@pytest.mark.slow
def test_chaos_open_loop_qps_ramp(dyadic, dyadic_v2):
    """Full open-loop QPS ramp with chaos at stage boundaries: faults
    at the second stage, hot-swap at the third. Zero drops and p99
    under load are recorded; bit identity holds across the swap."""
    bst, X = dyadic
    bst2, _ = dyadic_v2
    faults.clear()
    with Server(min_bucket=4, max_bucket=256, n_replicas=2,
                retry_attempts=1, breaker_threshold=2,
                breaker_cooldown_ms=50.0, max_queue=2048) as srv:
        srv.load_model("m", booster=bst)

        def _chaos(stage):
            if stage == 1:
                faults.schedule("serving_replica_predict", fail=4)
            elif stage == 2:
                srv.hot_swap("m", booster=bst2)

        res = run_open_loop(srv, "m", X,
                            stages=[(50, 2.0), (150, 2.0), (300, 2.0)],
                            max_rows=48, raw_score=True,
                            timeout_s=60.0, seed=4, mid_run=_chaos)
        assert res.dropped == 0, res.by_outcome()
        assert res.by_outcome().get("error", 0) == 0
        for rec in res.ok_records():
            val = np.asarray(rec.value)
            assert (np.array_equal(
                        val, bst.predict(X[rec.lo:rec.hi],
                                         raw_score=True)) or
                    np.array_equal(
                        val, bst2.predict(X[rec.lo:rec.hi],
                                          raw_score=True)))
        pct = res.latency_percentiles()
        assert pct["p99_ms"] > 0.0
        assert faults.trips("serving_replica_predict") >= 1
    faults.clear()
