"""Serving engine tests: registry lifecycle, bucket cache bounds,
padded-row bit-identity, micro-batch coalescing, overload shedding,
CPU-fallback parity, metrics snapshot schema, CLI task=serve."""

import json
import os
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (BucketedPredictor, MicroBatcher,
                                  ModelRegistry, OverloadError, Server,
                                  build_device_forest, max_compilations,
                                  next_bucket)
from tests.conftest import make_binary, make_multiclass, make_regression

RTOL, ATOL = 1e-5, 1e-7


def _train(objective="binary", n=400, f=8, seed=0, rounds=8, **extra):
    if objective == "multiclass":
        X, y = make_multiclass(n=n, f=f, k=3, seed=seed)
        params = {"objective": "multiclass", "num_class": 3}
    elif objective == "regression":
        X, y = make_regression(n=n, f=f, seed=seed)
        params = {"objective": "regression"}
    else:
        X, y = make_binary(n=n, f=f, seed=seed)
        params = {"objective": "binary"}
    params.update({"num_leaves": 15, "min_data_in_leaf": 5,
                   "verbosity": -1}, **extra)
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=rounds)
    return bst, X, y


@pytest.fixture(scope="module")
def binary_model():
    return _train("binary")


# ---------------------------------------------------------------------------
# shape bucketing


def test_next_bucket_and_bound():
    assert next_bucket(1, 4, 64) == 4
    assert next_bucket(4, 4, 64) == 4
    assert next_bucket(5, 4, 64) == 8
    assert next_bucket(64, 4, 64) == 64
    assert next_bucket(1000, 4, 64) == 64   # clamped: engine chunks
    assert max_compilations(64) == 7        # log2(64) + 1
    assert max_compilations(1) == 2


def test_bucket_cache_bounds_compilations(binary_model):
    """Mixed batch sizes 1..N hit at most log2(max_bucket)+1 buckets,
    and the compile counter stops growing after warmup."""
    bst, X, _ = binary_model
    forest = bst.device_forest()
    engine = BucketedPredictor(min_bucket=4, max_bucket=64)
    sizes = [1, 2, 3, 5, 9, 17, 33, 64, 150, 400, 7, 40, 1, 64]
    for s in sizes:
        engine.predict_raw(forest, forest.bin_rows(X[:s]))
    bound = max_compilations(64)
    assert engine.compile_count <= bound
    # warmup done: every bucket has been seen, so replaying the stream
    # is pure cache hits
    before = engine.compile_count
    for s in sizes:
        engine.predict_raw(forest, forest.bin_rows(X[:s]))
    assert engine.compile_count == before
    assert engine.hit_count > 0


def test_padded_rows_bit_identical(binary_model):
    """Bucket padding is invisible: real rows of a padded batch equal
    the unpadded batch bit-for-bit (satellite: learner/predict.py
    row_valid masking)."""
    import jax.numpy as jnp
    from lightgbm_tpu.learner.predict import predict_binned_forest

    bst, X, _ = binary_model
    forest = bst.device_forest()
    bins = forest.bin_rows(X[:37])
    unpadded = np.asarray(predict_binned_forest(
        forest.stacked, forest.tree_class, jnp.asarray(bins),
        forest.num_bins, forest.missing_is_nan,
        num_outputs=forest.num_outputs))
    padded_bins = np.concatenate(
        [bins, np.zeros((64 - 37, bins.shape[1]), bins.dtype)])
    valid = jnp.asarray(np.arange(64) < 37)
    padded = np.asarray(predict_binned_forest(
        forest.stacked, forest.tree_class, jnp.asarray(padded_bins),
        forest.num_bins, forest.missing_is_nan,
        num_outputs=forest.num_outputs, row_valid=valid))
    assert np.array_equal(padded[:37], unpadded)     # bit-identical
    assert np.all(padded[37:] == 0.0)                # pad rows inert


# ---------------------------------------------------------------------------
# registry lifecycle


def test_registry_load_get_evict(binary_model):
    bst, _, _ = binary_model
    reg = ModelRegistry(max_models=4)
    entry = reg.load("m", booster=bst)
    assert entry.version == 1 and entry.forest.supported
    assert "m" in reg and len(reg) == 1
    assert reg.get("m") is entry
    assert reg.evict("m") is True
    assert reg.evict("m") is False
    with pytest.raises(lgb.LightGBMError):
        reg.get("m")


def test_registry_refresh_bumps_version(binary_model):
    bst, _, _ = binary_model
    reg = ModelRegistry()
    reg.load("m", booster=bst)
    e2 = reg.refresh("m", booster=bst)
    assert e2.version == 2
    with pytest.raises(lgb.LightGBMError):
        reg.refresh("ghost", booster=bst)


def test_registry_lru_capacity(binary_model):
    bst, _, _ = binary_model
    reg = ModelRegistry(max_models=2)
    reg.load("a", booster=bst)
    reg.load("b", booster=bst)
    reg.get("a")                      # b becomes LRU
    reg.load("c", booster=bst)
    assert reg.names() == ["a", "c"]


def test_registry_load_from_model_str(binary_model, tmp_path):
    bst, X, _ = binary_model
    reg = ModelRegistry()
    entry = reg.load("s", model_str=bst.model_to_string())
    assert entry.forest.supported
    path = tmp_path / "model.txt"
    bst.save_model(str(path))
    entry2 = reg.load("f", model_file=str(path))
    assert entry2.forest.num_trees == entry.forest.num_trees


def test_device_forest_memoized_and_invalidated():
    X, y = make_binary(n=300, f=6, seed=3)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=2,
                    keep_training_booster=True)
    f1 = bst.device_forest()
    assert bst.device_forest() is f1          # memoized
    bst.update()                              # mutation invalidates
    f2 = bst.device_forest()
    assert f2 is not f1
    assert f2.num_trees == f1.num_trees + 1


# ---------------------------------------------------------------------------
# micro-batching


def test_microbatcher_coalesces_in_fifo_order():
    calls = []

    def run(bins):
        calls.append(len(bins))
        return bins.astype(np.float32) * 2.0

    b = MicroBatcher(run, max_batch_size=100, max_wait_ms=50.0,
                     max_queue=16, name="t")
    try:
        b.pause()
        reqs = [np.full((i + 1, 2), i, np.int32) for i in range(4)]
        futs = [b.submit(r) for r in reqs]
        assert b.queue_depth() == 4
        b.resume()
        outs = [f.result(timeout=10) for f in futs]
        # one coalesced device batch served all four requests...
        assert calls == [sum(len(r) for r in reqs)]
        assert b.batch_count == 1 and b.coalesced_requests == 4
        # ...and each caller got exactly its slice, in submit order
        for i, (r, o) in enumerate(zip(reqs, outs)):
            assert o.shape[0] == len(r)
            assert np.all(o == 2.0 * i)
    finally:
        b.close()


def test_microbatcher_respects_max_batch_size():
    calls = []

    def run(bins):
        calls.append(len(bins))
        return bins.astype(np.float32)

    b = MicroBatcher(run, max_batch_size=5, max_wait_ms=50.0, name="t")
    try:
        b.pause()
        futs = [b.submit(np.zeros((3, 1), np.int32)) for _ in range(3)]
        b.resume()
        for f in futs:
            f.result(timeout=10)
        # 3+3 > 5, so the first batch holds one request... but any split
        # preserving request atomicity and order is acceptable
        assert sum(calls) == 9
        assert all(c <= 5 or c == 3 for c in calls)
        assert len(calls) >= 2
    finally:
        b.close()


def test_microbatcher_sheds_past_queue_depth():
    def run(bins):
        return bins.astype(np.float32)

    b = MicroBatcher(run, max_batch_size=8, max_wait_ms=5.0,
                     max_queue=2, name="t")
    try:
        b.pause()                      # worker frozen: queue only fills
        b.submit(np.zeros((1, 1), np.int32))
        b.submit(np.zeros((1, 1), np.int32))
        with pytest.raises(OverloadError):
            b.submit(np.zeros((1, 1), np.int32))
        assert b.shed_count == 1
    finally:
        b.close()


# ---------------------------------------------------------------------------
# the Server facade


def test_server_parity_mixed_sizes(binary_model):
    bst, X, _ = binary_model
    with Server(min_bucket=4, max_bucket=64, max_wait_ms=1.0) as srv:
        srv.load_model("m", booster=bst)
        lo = 0
        for s in [1, 3, 17, 64, 120, 2, 33]:
            sl = X[lo % 200: lo % 200 + s]
            got = srv.predict("m", sl)
            ref = bst.predict(sl)
            assert got.shape == ref.shape
            np.testing.assert_allclose(got, ref, rtol=RTOL, atol=ATOL)
            lo += s
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["buckets_compiled"] <= snap["max_compilations"]


def test_server_parity_multiclass_and_raw():
    bst, X, _ = _train("multiclass", n=300, rounds=4)
    with Server(min_bucket=4, max_bucket=64) as srv:
        srv.load_model("mc", booster=bst)
        got = srv.predict("mc", X[:29])
        ref = bst.predict(X[:29])
        assert got.shape == ref.shape == (29, 3)
        np.testing.assert_allclose(got, ref, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(
            srv.predict("mc", X[:9], raw_score=True),
            bst.predict(X[:9], raw_score=True), rtol=RTOL, atol=1e-6)


def test_server_parity_categorical_nan_unseen():
    r = np.random.RandomState(7)
    X = r.randn(400, 5)
    X[:, 2] = r.randint(0, 12, 400)
    X[r.rand(400) < 0.15, 0] = np.nan
    y = ((X[:, 2] % 3 == 0) + 0.1 * np.nan_to_num(X[:, 0])) \
        .astype(np.float32)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    lgb.Dataset(X, label=y, categorical_feature=[2]),
                    num_boost_round=6)
    Xq = X[:60].copy()
    Xq[0, 2] = 99          # unseen category -> right child
    Xq[1, 2] = np.nan      # NaN category -> right child
    with Server(min_bucket=4, max_bucket=64) as srv:
        srv.load_model("cat", booster=bst)
        np.testing.assert_allclose(srv.predict("cat", Xq),
                                   bst.predict(Xq), rtol=RTOL, atol=ATOL)


def test_server_file_loaded_model_parity(binary_model, tmp_path):
    """A model re-loaded from text (no training BinMappers) serves via
    threshold-reconstruction binning with full parity."""
    bst, X, _ = binary_model
    path = tmp_path / "m.txt"
    bst.save_model(str(path))
    with Server(min_bucket=4, max_bucket=64) as srv:
        srv.load_model("f", model_file=str(path))
        np.testing.assert_allclose(srv.predict("f", X[:77]),
                                   bst.predict(X[:77]),
                                   rtol=RTOL, atol=ATOL)


def test_server_cpu_fallback_parity(binary_model, monkeypatch):
    """Device failure falls back to the host predict path (results
    still exactly match Booster.predict), consecutive failures open
    the replica breaker, and the breaker self-heals once the device
    recovers — no manual refresh needed (contrast the PR-1 sticky
    degraded flag)."""
    bst, X, _ = binary_model
    with Server(min_bucket=4, max_bucket=64, retry_attempts=1,
                breaker_threshold=2, breaker_cooldown_ms=150.0) as srv:
        srv.load_model("m", booster=bst)

        def boom(*a, **k):
            raise RuntimeError("device lost")

        monkeypatch.setattr(srv.engine, "predict_raw", boom)
        got = srv.predict("m", X[:21])
        ref = bst.predict(X[:21])
        assert np.array_equal(got, ref)   # identical: same host code path
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["fallback_count"] >= 1
        # a second failing dispatch reaches the 2-failure threshold:
        # the replica breaker opens and the entry degrades (derived,
        # not sticky)
        got2 = srv.predict("m", X[:5])
        assert np.array_equal(got2, bst.predict(X[:5]))
        breaker = srv.replicas("m").replicas()[0].breaker
        assert breaker.state == "open"
        assert srv.metrics_snapshot("m")["models"]["m"]["degraded"] \
            is True
        # device recovers: once the cooldown elapses the next dispatch
        # is a half-open probe, and one clean batch re-closes the
        # breaker — self-healing, no refresh_model required
        monkeypatch.undo()
        time.sleep(0.2)
        got3 = srv.predict("m", X[:9])
        # device path again (f32 accumulation): tolerance, not bits
        np.testing.assert_allclose(got3, bst.predict(X[:9]),
                                   rtol=RTOL, atol=ATOL)
        # the probe dispatch may have been the healing one; poke once
        # more to be robust to batching boundaries
        srv.predict("m", X[:3])
        assert breaker.state == "closed"
        assert breaker.opens >= 1 and breaker.closes >= 1
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["degraded"] is False


def test_server_unsupported_model_host_path():
    """Linear-leaf models cannot be served from bins; the server falls
    back to host predict transparently."""
    X, y = make_regression(n=300, f=5, seed=2)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "linear_tree": True, "verbosity": -1},
                    lgb.Dataset(X, label=y, params={"linear_tree": True}),
                    num_boost_round=3)
    forest = bst.device_forest()
    assert not forest.supported and "linear" in forest.unsupported_reason
    with Server() as srv:
        srv.load_model("lin", booster=bst)
        got = srv.predict("lin", X[:31])
        assert np.array_equal(got, bst.predict(X[:31]))
        snap = srv.metrics_snapshot("lin")["models"]["lin"]
        assert snap["device_resident"] is False
        assert snap["fallback_count"] == 1


def test_server_shedding_and_metrics(binary_model):
    bst, X, _ = binary_model
    with Server(min_bucket=4, max_bucket=64, max_queue=2,
                max_wait_ms=50.0) as srv:
        srv.load_model("m", booster=bst)
        srv.batcher("m").pause()
        futs = [srv.predict_async("m", X[:3]) for _ in range(2)]
        with pytest.raises(OverloadError):
            srv.predict("m", X[:3])
        srv.batcher("m").resume()
        for f in futs:
            f.result(timeout=10)
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["shed_count"] == 1
        assert snap["requests"] == 2


def test_server_evict_and_metrics_schema(binary_model):
    bst, X, _ = binary_model
    with Server(min_bucket=4, max_bucket=32) as srv:
        srv.load_model("m", booster=bst)
        srv.predict("m", X[:10])
        srv.predict("m", X[:10])
        snap = srv.metrics_snapshot()
        m = snap["models"]["m"]
        for key in ("requests", "rows", "qps", "rows_per_sec", "p50_ms",
                    "p95_ms", "p99_ms", "bucket_cache_hits",
                    "compile_count", "shed_count", "fallback_count",
                    "queue_depth", "version"):
            assert key in m, key
        assert m["requests"] == 2 and m["rows"] == 20
        assert m["p50_ms"] is not None
        assert snap["engine"]["max_compilations_per_model"] == \
            max_compilations(32)
        json.dumps(snap)                      # snapshot is JSON-able
        assert srv.evict_model("m") is True
        assert srv.evict_model("m") is False
        with pytest.raises(lgb.LightGBMError):
            srv.predict("m", X[:2])


def test_server_save_metrics(binary_model, tmp_path):
    bst, X, _ = binary_model
    path = tmp_path / "metrics.json"
    with Server(min_bucket=4, max_bucket=32) as srv:
        srv.load_model("m", booster=bst)
        srv.predict("m", X[:5])
        srv.save_metrics(str(path))
    snap = json.loads(path.read_text())
    assert snap["models"]["m"]["requests"] == 1
    assert "timers" in snap


def test_build_device_forest_no_trees():
    from lightgbm_tpu.tree import HostModel
    m = HostModel()
    m.max_feature_idx = 3
    forest = build_device_forest(m)
    assert not forest.supported


# ---------------------------------------------------------------------------
# CLI task=serve


def test_cli_task_serve(tmp_path):
    from lightgbm_tpu.cli import main as cli_main

    X, y = make_binary(n=200, f=5, seed=4)
    data = np.column_stack([y, X])
    train_file = tmp_path / "train.csv"
    np.savetxt(train_file, data, delimiter=",", fmt="%.8g")
    model_file = tmp_path / "model.txt"
    assert cli_main([f"data={train_file}", "task=train",
                     "objective=binary", "num_leaves=7",
                     "num_iterations=3", "verbosity=-1", "min_data=5",
                     f"output_model={model_file}"]) == 0
    out_file = tmp_path / "preds.tsv"
    assert cli_main([f"data={train_file}", "task=serve",
                     f"input_model={model_file}",
                     f"output_result={out_file}", "verbosity=-1",
                     "max_bucket=64", "min_bucket=4"]) == 0
    preds = np.loadtxt(out_file)
    assert preds.shape == (200,)
    bst = lgb.Booster(model_file=str(model_file))
    np.testing.assert_allclose(preds, bst.predict(X), rtol=RTOL,
                               atol=1e-6)
    metrics_path = str(out_file) + ".metrics.json"
    assert os.path.exists(metrics_path)
    snap = json.loads(open(metrics_path).read())
    m = snap["models"]["default"]
    assert m["rows"] == 200 and m["shed_count"] == 0
    assert m["buckets_compiled"] <= snap["engine"][
        "max_compilations_per_model"]


# ---------------------------------------------------------------------------
# circuit breaker (serving/breaker.py)


class _FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_breaker_opens_on_consecutive_failures_only():
    from lightgbm_tpu.serving import CircuitBreaker
    clk = _FakeClock()
    br = CircuitBreaker(threshold=3, cooldown_s=1.0, clock=clk)
    assert br.state == "closed" and br.try_acquire()
    br.record_failure()
    br.record_failure()
    br.record_success()          # resets the consecutive count
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"  # 2 consecutive < threshold 3
    br.record_failure()
    assert br.state == "open" and br.opens == 1
    # open refuses until the cooldown elapses
    assert not br.try_acquire() and not br.available()
    clk.t += 1.5
    assert br.available()


def test_breaker_half_open_single_probe_and_heal():
    from lightgbm_tpu.serving import CircuitBreaker, breaker_state_code
    clk = _FakeClock()
    br = CircuitBreaker(threshold=1, cooldown_s=1.0, clock=clk)
    br.record_failure()
    assert br.state == "open"
    clk.t += 2.0
    assert br.try_acquire()           # the single half-open probe
    assert br.state == "half_open"
    assert not br.try_acquire()       # concurrent dispatch refused
    br.record_success()
    assert br.state == "closed" and br.closes == 1 and br.probes == 1
    snap = br.snapshot()
    assert snap["state_code"] == breaker_state_code("closed") == 0


def test_breaker_probe_failure_reopens():
    from lightgbm_tpu.serving import CircuitBreaker
    clk = _FakeClock()
    br = CircuitBreaker(threshold=1, cooldown_s=1.0, clock=clk)
    br.record_failure()
    clk.t += 1.1
    assert br.try_acquire()
    br.record_failure()               # probe failed
    assert br.state == "open" and br.opens == 2
    assert not br.try_acquire()       # cooldown restarted
    clk.t += 1.1
    assert br.try_acquire()
    br.record_success()
    assert br.state == "closed"


def test_breaker_force_open():
    from lightgbm_tpu.serving import CircuitBreaker
    br = CircuitBreaker(threshold=5, cooldown_s=60.0)
    br.force_open()
    assert br.state == "open" and not br.available()


# ---------------------------------------------------------------------------
# SLO deadlines (serving/batcher.py + server policy)


def test_deadline_shed_at_admission():
    """With the worker paused and the queue non-empty, a request whose
    budget is below the projected wait is shed at submit."""
    from lightgbm_tpu.serving import DeadlineExceeded

    done = []
    b = MicroBatcher(lambda bins: np.zeros((len(bins), 1)),
                     max_batch_size=8, max_wait_ms=1.0, name="slo")
    try:
        b.pause()
        bins = np.zeros((4, 3), np.int32)
        f1 = b.submit(bins, deadline=None)          # no budget: queues
        with pytest.raises(DeadlineExceeded):
            # 0.1ms budget cannot cover even one EMA service time
            b.submit(bins, deadline=time.monotonic() + 1e-4)
        assert b.deadline_shed_count == 1
        # a generous budget is admitted
        f2 = b.submit(bins, deadline=time.monotonic() + 60.0)
        b.resume()
        assert f1.result(timeout=5.0).shape == (4, 1)
        assert f2.result(timeout=5.0).shape == (4, 1)
        done.append(True)
    finally:
        b.close()
    assert done


def test_deadline_expiry_in_queue():
    """A request admitted but stuck past its deadline expires at
    dispatch with DeadlineExceeded — never silently dropped."""
    from lightgbm_tpu.serving import DeadlineExceeded

    b = MicroBatcher(lambda bins: np.zeros((len(bins), 1)),
                     max_batch_size=8, max_wait_ms=1.0, name="slo2")
    try:
        b.pause()
        bins = np.zeros((2, 3), np.int32)
        fut = b.submit(bins, deadline=time.monotonic() + 0.05)
        time.sleep(0.15)                  # let it expire while paused
        b.resume()
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=5.0)
        assert b.deadline_expired_count == 1
    finally:
        b.close()


def test_server_deadline_policy_fallback_and_fail(binary_model):
    """Policy 'fallback' answers a blown-budget request via host
    predict (counted as a deadline miss); policy 'fail' raises."""
    from lightgbm_tpu.serving import DeadlineExceeded
    bst, X, _ = binary_model
    with Server(min_bucket=4, max_bucket=64, slo_ms=0.001,
                deadline_policy="fallback") as srv:
        srv.load_model("m", booster=bst)
        srv.batcher("m").pause()          # make the projection hopeless
        srv.predict("m", X[:4])           # seed the queue
        got = srv.predict("m", X[:7])
        assert np.array_equal(got, bst.predict(X[:7]))
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["deadline_misses"] >= 1
        assert snap["fallback_count"] >= 1
    with Server(min_bucket=4, max_bucket=64, slo_ms=0.001,
                deadline_policy="fail") as srv:
        srv.load_model("m", booster=bst)
        srv.batcher("m").pause()
        try:
            srv.predict("m", X[:4])
        except DeadlineExceeded:
            pass
        with pytest.raises(DeadlineExceeded):
            srv.predict("m", X[:7])


# ---------------------------------------------------------------------------
# replica failover + hot swap + drain races


def test_replica_failover_on_injected_faults(binary_model):
    """With 2 replicas and injected faults on replica dispatch, the
    batch fails over and still answers; failovers are counted."""
    from lightgbm_tpu.reliability import faults
    bst, X, _ = binary_model
    with Server(min_bucket=4, max_bucket=64, n_replicas=2,
                retry_attempts=1, breaker_threshold=1,
                breaker_cooldown_ms=60000.0) as srv:
        srv.load_model("m", booster=bst)
        assert len(srv.replicas("m")) == 2
        with faults.injected("serving_replica_predict", fail=1):
            got = srv.predict("m", X[:9])
        np.testing.assert_allclose(got, bst.predict(X[:9]), rtol=RTOL,
                                   atol=ATOL)
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["failovers"] >= 1
        assert snap["breaker_open_replicas"] == 1
        states = {r["replica"]: r["state"] for r in snap["replicas"]}
        assert "open" in states.values() and "closed" in states.values()
        # the open replica is out of rotation; traffic still flows
        got2 = srv.predict("m", X[:5])
        np.testing.assert_allclose(got2, bst.predict(X[:5]), rtol=RTOL,
                                   atol=ATOL)


def test_hot_swap_drains_queue_through_old_model(binary_model):
    """Queued requests at hot-swap resolve via the OLD entry's host
    path (bit-identical to the old booster), new requests hit the new
    version — zero drops, no torn model."""
    bst, X, _ = binary_model
    X2, y2 = make_binary(n=400, f=X.shape[1], seed=99)
    bst2 = lgb.train({"objective": "binary", "num_leaves": 9,
                      "verbosity": -1}, lgb.Dataset(X2, label=y2),
                     num_boost_round=5)
    with Server(min_bucket=4, max_bucket=64) as srv:
        srv.load_model("m", booster=bst)
        srv.batcher("m").pause()
        futs = [srv.predict_async("m", X[i:i + 3]) for i in range(6)]
        entry = srv.hot_swap("m", booster=bst2)
        assert entry.version == 2
        for i, f in enumerate(futs):
            got = f.result(timeout=10.0)
            assert np.array_equal(got, bst.predict(X[i:i + 3]))
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["swap_drains"] == 6
        assert snap["requests"] == 6          # each counted exactly once
        got_new = srv.predict("m", X[:11])
        np.testing.assert_allclose(got_new, bst2.predict(X[:11]),
                                   rtol=RTOL, atol=ATOL)
        assert srv.metrics_snapshot("m")["models"]["m"]["version"] == 2


def test_batcher_closed_drain_races_concurrent_evict(binary_model):
    """The satellite race: queued futures vs a concurrent registry
    evict. Every future resolves (host path), none hangs, and the
    metrics account each request exactly once."""
    import threading
    bst, X, _ = binary_model
    with Server(min_bucket=4, max_bucket=64) as srv:
        entry = srv.load_model("m", booster=bst)
        srv.batcher("m").pause()
        futs = [srv.predict_async("m", X[i:i + 2]) for i in range(8)]
        stop = threading.Event()
        racers = []

        def _evict():
            stop.wait()
            srv.evict_model("m")

        def _late_submits():
            stop.wait()
            # these race the close: either queued-then-drained or
            # refused with BatcherClosed at submit — both host-resolve
            for i in range(4):
                futs.append(srv.predict_async("m", X[i:i + 2]))

        racers = [threading.Thread(target=_evict),
                  threading.Thread(target=_late_submits)]
        for t in racers:
            t.start()
        stop.set()
        for t in racers:
            t.join(timeout=10.0)
        for i, f in enumerate(futs):
            got = f.result(timeout=10.0)
            assert np.array_equal(got, bst.predict(X[i % 8:i % 8 + 2])) \
                or got.shape == (2,)
        # exactly-once accounting on the evicted entry's metrics
        assert entry.metrics.requests == len(futs)
        assert "m" not in srv.registry.names()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_batcher_worker_death_flightrec_and_no_hang(tmp_path):
    """A batcher worker thread dying flushes a postmortem bundle and
    resolves every queued future with BatcherClosed — nothing hangs."""
    from lightgbm_tpu.observability.flightrec import recorder
    from lightgbm_tpu.serving import BatcherClosed

    recorder.configure(enabled=True, out_dir=str(tmp_path))
    recorder.reset()

    def _die(bins):
        raise KeyboardInterrupt("worker killed")   # escapes Exception

    b = MicroBatcher(_die, max_batch_size=4, max_wait_ms=0.5,
                     name="doomed")
    fut = b.submit(np.zeros((2, 3), np.int32))
    with pytest.raises(BatcherClosed):
        fut.result(timeout=10.0)
    # callers are unblocked first; the post-mortem flush lands moments
    # later on the dying worker thread
    deadline = time.monotonic() + 5.0
    bundles = []
    while not bundles and time.monotonic() < deadline:
        bundles = list(tmp_path.glob("postmortem_*.json"))
        time.sleep(0.02)
    assert bundles, "worker death must flush a flight-recorder bundle"
    rec = json.loads(bundles[0].read_text())
    evs = [e for e in rec["events"] if e.get("kind") == "exception"]
    assert any("serving_batcher_worker" in e.get("name", "")
               for e in evs)
    recorder.configure(out_dir="")
    with pytest.raises(BatcherClosed):
        b.submit(np.zeros((1, 3), np.int32))


def test_prometheus_replica_breaker_rows(binary_model):
    """Per-replica breaker gauges are exported with model+replica
    labels under the lightgbm_tpu_serving_replica family."""
    bst, X, _ = binary_model
    with Server(min_bucket=4, max_bucket=64, n_replicas=2) as srv:
        srv.load_model("m", booster=bst)
        srv.predict("m", X[:5])
        text = srv.prometheus_text()
    assert ('lightgbm_tpu_serving_replica_breaker_state'
            '{model="m",replica="0"} 0') in text
    assert ('lightgbm_tpu_serving_replica_breaker_state'
            '{model="m",replica="1"} 0') in text
    assert 'lightgbm_tpu_serving_model_deadline_misses{model="m"}' \
        in text
    assert 'lightgbm_tpu_serving_model_failovers{model="m"}' in text
    assert 'lightgbm_tpu_serving_model_swap_drains{model="m"}' in text
