"""Serving engine tests: registry lifecycle, bucket cache bounds,
padded-row bit-identity, micro-batch coalescing, overload shedding,
CPU-fallback parity, metrics snapshot schema, CLI task=serve."""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (BucketedPredictor, MicroBatcher,
                                  ModelRegistry, OverloadError, Server,
                                  build_device_forest, max_compilations,
                                  next_bucket)
from tests.conftest import make_binary, make_multiclass, make_regression

RTOL, ATOL = 1e-5, 1e-7


def _train(objective="binary", n=400, f=8, seed=0, rounds=8, **extra):
    if objective == "multiclass":
        X, y = make_multiclass(n=n, f=f, k=3, seed=seed)
        params = {"objective": "multiclass", "num_class": 3}
    elif objective == "regression":
        X, y = make_regression(n=n, f=f, seed=seed)
        params = {"objective": "regression"}
    else:
        X, y = make_binary(n=n, f=f, seed=seed)
        params = {"objective": "binary"}
    params.update({"num_leaves": 15, "min_data_in_leaf": 5,
                   "verbosity": -1}, **extra)
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=rounds)
    return bst, X, y


@pytest.fixture(scope="module")
def binary_model():
    return _train("binary")


# ---------------------------------------------------------------------------
# shape bucketing


def test_next_bucket_and_bound():
    assert next_bucket(1, 4, 64) == 4
    assert next_bucket(4, 4, 64) == 4
    assert next_bucket(5, 4, 64) == 8
    assert next_bucket(64, 4, 64) == 64
    assert next_bucket(1000, 4, 64) == 64   # clamped: engine chunks
    assert max_compilations(64) == 7        # log2(64) + 1
    assert max_compilations(1) == 2


def test_bucket_cache_bounds_compilations(binary_model):
    """Mixed batch sizes 1..N hit at most log2(max_bucket)+1 buckets,
    and the compile counter stops growing after warmup."""
    bst, X, _ = binary_model
    forest = bst.device_forest()
    engine = BucketedPredictor(min_bucket=4, max_bucket=64)
    sizes = [1, 2, 3, 5, 9, 17, 33, 64, 150, 400, 7, 40, 1, 64]
    for s in sizes:
        engine.predict_raw(forest, forest.bin_rows(X[:s]))
    bound = max_compilations(64)
    assert engine.compile_count <= bound
    # warmup done: every bucket has been seen, so replaying the stream
    # is pure cache hits
    before = engine.compile_count
    for s in sizes:
        engine.predict_raw(forest, forest.bin_rows(X[:s]))
    assert engine.compile_count == before
    assert engine.hit_count > 0


def test_padded_rows_bit_identical(binary_model):
    """Bucket padding is invisible: real rows of a padded batch equal
    the unpadded batch bit-for-bit (satellite: learner/predict.py
    row_valid masking)."""
    import jax.numpy as jnp
    from lightgbm_tpu.learner.predict import predict_binned_forest

    bst, X, _ = binary_model
    forest = bst.device_forest()
    bins = forest.bin_rows(X[:37])
    unpadded = np.asarray(predict_binned_forest(
        forest.stacked, forest.tree_class, jnp.asarray(bins),
        forest.num_bins, forest.missing_is_nan,
        num_outputs=forest.num_outputs))
    padded_bins = np.concatenate(
        [bins, np.zeros((64 - 37, bins.shape[1]), bins.dtype)])
    valid = jnp.asarray(np.arange(64) < 37)
    padded = np.asarray(predict_binned_forest(
        forest.stacked, forest.tree_class, jnp.asarray(padded_bins),
        forest.num_bins, forest.missing_is_nan,
        num_outputs=forest.num_outputs, row_valid=valid))
    assert np.array_equal(padded[:37], unpadded)     # bit-identical
    assert np.all(padded[37:] == 0.0)                # pad rows inert


# ---------------------------------------------------------------------------
# registry lifecycle


def test_registry_load_get_evict(binary_model):
    bst, _, _ = binary_model
    reg = ModelRegistry(max_models=4)
    entry = reg.load("m", booster=bst)
    assert entry.version == 1 and entry.forest.supported
    assert "m" in reg and len(reg) == 1
    assert reg.get("m") is entry
    assert reg.evict("m") is True
    assert reg.evict("m") is False
    with pytest.raises(lgb.LightGBMError):
        reg.get("m")


def test_registry_refresh_bumps_version(binary_model):
    bst, _, _ = binary_model
    reg = ModelRegistry()
    reg.load("m", booster=bst)
    e2 = reg.refresh("m", booster=bst)
    assert e2.version == 2
    with pytest.raises(lgb.LightGBMError):
        reg.refresh("ghost", booster=bst)


def test_registry_lru_capacity(binary_model):
    bst, _, _ = binary_model
    reg = ModelRegistry(max_models=2)
    reg.load("a", booster=bst)
    reg.load("b", booster=bst)
    reg.get("a")                      # b becomes LRU
    reg.load("c", booster=bst)
    assert reg.names() == ["a", "c"]


def test_registry_load_from_model_str(binary_model, tmp_path):
    bst, X, _ = binary_model
    reg = ModelRegistry()
    entry = reg.load("s", model_str=bst.model_to_string())
    assert entry.forest.supported
    path = tmp_path / "model.txt"
    bst.save_model(str(path))
    entry2 = reg.load("f", model_file=str(path))
    assert entry2.forest.num_trees == entry.forest.num_trees


def test_device_forest_memoized_and_invalidated():
    X, y = make_binary(n=300, f=6, seed=3)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=2,
                    keep_training_booster=True)
    f1 = bst.device_forest()
    assert bst.device_forest() is f1          # memoized
    bst.update()                              # mutation invalidates
    f2 = bst.device_forest()
    assert f2 is not f1
    assert f2.num_trees == f1.num_trees + 1


# ---------------------------------------------------------------------------
# micro-batching


def test_microbatcher_coalesces_in_fifo_order():
    calls = []

    def run(bins):
        calls.append(len(bins))
        return bins.astype(np.float32) * 2.0

    b = MicroBatcher(run, max_batch_size=100, max_wait_ms=50.0,
                     max_queue=16, name="t")
    try:
        b.pause()
        reqs = [np.full((i + 1, 2), i, np.int32) for i in range(4)]
        futs = [b.submit(r) for r in reqs]
        assert b.queue_depth() == 4
        b.resume()
        outs = [f.result(timeout=10) for f in futs]
        # one coalesced device batch served all four requests...
        assert calls == [sum(len(r) for r in reqs)]
        assert b.batch_count == 1 and b.coalesced_requests == 4
        # ...and each caller got exactly its slice, in submit order
        for i, (r, o) in enumerate(zip(reqs, outs)):
            assert o.shape[0] == len(r)
            assert np.all(o == 2.0 * i)
    finally:
        b.close()


def test_microbatcher_respects_max_batch_size():
    calls = []

    def run(bins):
        calls.append(len(bins))
        return bins.astype(np.float32)

    b = MicroBatcher(run, max_batch_size=5, max_wait_ms=50.0, name="t")
    try:
        b.pause()
        futs = [b.submit(np.zeros((3, 1), np.int32)) for _ in range(3)]
        b.resume()
        for f in futs:
            f.result(timeout=10)
        # 3+3 > 5, so the first batch holds one request... but any split
        # preserving request atomicity and order is acceptable
        assert sum(calls) == 9
        assert all(c <= 5 or c == 3 for c in calls)
        assert len(calls) >= 2
    finally:
        b.close()


def test_microbatcher_sheds_past_queue_depth():
    def run(bins):
        return bins.astype(np.float32)

    b = MicroBatcher(run, max_batch_size=8, max_wait_ms=5.0,
                     max_queue=2, name="t")
    try:
        b.pause()                      # worker frozen: queue only fills
        b.submit(np.zeros((1, 1), np.int32))
        b.submit(np.zeros((1, 1), np.int32))
        with pytest.raises(OverloadError):
            b.submit(np.zeros((1, 1), np.int32))
        assert b.shed_count == 1
    finally:
        b.close()


# ---------------------------------------------------------------------------
# the Server facade


def test_server_parity_mixed_sizes(binary_model):
    bst, X, _ = binary_model
    with Server(min_bucket=4, max_bucket=64, max_wait_ms=1.0) as srv:
        srv.load_model("m", booster=bst)
        lo = 0
        for s in [1, 3, 17, 64, 120, 2, 33]:
            sl = X[lo % 200: lo % 200 + s]
            got = srv.predict("m", sl)
            ref = bst.predict(sl)
            assert got.shape == ref.shape
            np.testing.assert_allclose(got, ref, rtol=RTOL, atol=ATOL)
            lo += s
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["buckets_compiled"] <= snap["max_compilations"]


def test_server_parity_multiclass_and_raw():
    bst, X, _ = _train("multiclass", n=300, rounds=4)
    with Server(min_bucket=4, max_bucket=64) as srv:
        srv.load_model("mc", booster=bst)
        got = srv.predict("mc", X[:29])
        ref = bst.predict(X[:29])
        assert got.shape == ref.shape == (29, 3)
        np.testing.assert_allclose(got, ref, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(
            srv.predict("mc", X[:9], raw_score=True),
            bst.predict(X[:9], raw_score=True), rtol=RTOL, atol=1e-6)


def test_server_parity_categorical_nan_unseen():
    r = np.random.RandomState(7)
    X = r.randn(400, 5)
    X[:, 2] = r.randint(0, 12, 400)
    X[r.rand(400) < 0.15, 0] = np.nan
    y = ((X[:, 2] % 3 == 0) + 0.1 * np.nan_to_num(X[:, 0])) \
        .astype(np.float32)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "min_data_in_leaf": 5, "verbosity": -1},
                    lgb.Dataset(X, label=y, categorical_feature=[2]),
                    num_boost_round=6)
    Xq = X[:60].copy()
    Xq[0, 2] = 99          # unseen category -> right child
    Xq[1, 2] = np.nan      # NaN category -> right child
    with Server(min_bucket=4, max_bucket=64) as srv:
        srv.load_model("cat", booster=bst)
        np.testing.assert_allclose(srv.predict("cat", Xq),
                                   bst.predict(Xq), rtol=RTOL, atol=ATOL)


def test_server_file_loaded_model_parity(binary_model, tmp_path):
    """A model re-loaded from text (no training BinMappers) serves via
    threshold-reconstruction binning with full parity."""
    bst, X, _ = binary_model
    path = tmp_path / "m.txt"
    bst.save_model(str(path))
    with Server(min_bucket=4, max_bucket=64) as srv:
        srv.load_model("f", model_file=str(path))
        np.testing.assert_allclose(srv.predict("f", X[:77]),
                                   bst.predict(X[:77]),
                                   rtol=RTOL, atol=ATOL)


def test_server_cpu_fallback_parity(binary_model, monkeypatch):
    """Device failure degrades to the host predict path; results still
    exactly match Booster.predict."""
    bst, X, _ = binary_model
    with Server(min_bucket=4, max_bucket=64) as srv:
        srv.load_model("m", booster=bst)

        def boom(*a, **k):
            raise RuntimeError("device lost")

        monkeypatch.setattr(srv.engine, "predict_raw", boom)
        got = srv.predict("m", X[:21])
        ref = bst.predict(X[:21])
        assert np.array_equal(got, ref)   # identical: same host code path
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["degraded"] is True
        assert snap["fallback_count"] >= 1 and snap["errors"] >= 1
        # degraded entries skip the device entirely from then on
        got2 = srv.predict("m", X[:5])
        assert np.array_equal(got2, bst.predict(X[:5]))
        # refresh clears the degradation
        monkeypatch.undo()
        srv.refresh_model("m", booster=bst)
        assert srv.metrics_snapshot("m")["models"]["m"]["degraded"] is False


def test_server_unsupported_model_host_path():
    """Linear-leaf models cannot be served from bins; the server falls
    back to host predict transparently."""
    X, y = make_regression(n=300, f=5, seed=2)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "linear_tree": True, "verbosity": -1},
                    lgb.Dataset(X, label=y, params={"linear_tree": True}),
                    num_boost_round=3)
    forest = bst.device_forest()
    assert not forest.supported and "linear" in forest.unsupported_reason
    with Server() as srv:
        srv.load_model("lin", booster=bst)
        got = srv.predict("lin", X[:31])
        assert np.array_equal(got, bst.predict(X[:31]))
        snap = srv.metrics_snapshot("lin")["models"]["lin"]
        assert snap["device_resident"] is False
        assert snap["fallback_count"] == 1


def test_server_shedding_and_metrics(binary_model):
    bst, X, _ = binary_model
    with Server(min_bucket=4, max_bucket=64, max_queue=2,
                max_wait_ms=50.0) as srv:
        srv.load_model("m", booster=bst)
        srv.batcher("m").pause()
        futs = [srv.predict_async("m", X[:3]) for _ in range(2)]
        with pytest.raises(OverloadError):
            srv.predict("m", X[:3])
        srv.batcher("m").resume()
        for f in futs:
            f.result(timeout=10)
        snap = srv.metrics_snapshot("m")["models"]["m"]
        assert snap["shed_count"] == 1
        assert snap["requests"] == 2


def test_server_evict_and_metrics_schema(binary_model):
    bst, X, _ = binary_model
    with Server(min_bucket=4, max_bucket=32) as srv:
        srv.load_model("m", booster=bst)
        srv.predict("m", X[:10])
        srv.predict("m", X[:10])
        snap = srv.metrics_snapshot()
        m = snap["models"]["m"]
        for key in ("requests", "rows", "qps", "rows_per_sec", "p50_ms",
                    "p95_ms", "p99_ms", "bucket_cache_hits",
                    "compile_count", "shed_count", "fallback_count",
                    "queue_depth", "version"):
            assert key in m, key
        assert m["requests"] == 2 and m["rows"] == 20
        assert m["p50_ms"] is not None
        assert snap["engine"]["max_compilations_per_model"] == \
            max_compilations(32)
        json.dumps(snap)                      # snapshot is JSON-able
        assert srv.evict_model("m") is True
        assert srv.evict_model("m") is False
        with pytest.raises(lgb.LightGBMError):
            srv.predict("m", X[:2])


def test_server_save_metrics(binary_model, tmp_path):
    bst, X, _ = binary_model
    path = tmp_path / "metrics.json"
    with Server(min_bucket=4, max_bucket=32) as srv:
        srv.load_model("m", booster=bst)
        srv.predict("m", X[:5])
        srv.save_metrics(str(path))
    snap = json.loads(path.read_text())
    assert snap["models"]["m"]["requests"] == 1
    assert "timers" in snap


def test_build_device_forest_no_trees():
    from lightgbm_tpu.tree import HostModel
    m = HostModel()
    m.max_feature_idx = 3
    forest = build_device_forest(m)
    assert not forest.supported


# ---------------------------------------------------------------------------
# CLI task=serve


def test_cli_task_serve(tmp_path):
    from lightgbm_tpu.cli import main as cli_main

    X, y = make_binary(n=200, f=5, seed=4)
    data = np.column_stack([y, X])
    train_file = tmp_path / "train.csv"
    np.savetxt(train_file, data, delimiter=",", fmt="%.8g")
    model_file = tmp_path / "model.txt"
    assert cli_main([f"data={train_file}", "task=train",
                     "objective=binary", "num_leaves=7",
                     "num_iterations=3", "verbosity=-1", "min_data=5",
                     f"output_model={model_file}"]) == 0
    out_file = tmp_path / "preds.tsv"
    assert cli_main([f"data={train_file}", "task=serve",
                     f"input_model={model_file}",
                     f"output_result={out_file}", "verbosity=-1",
                     "max_bucket=64", "min_bucket=4"]) == 0
    preds = np.loadtxt(out_file)
    assert preds.shape == (200,)
    bst = lgb.Booster(model_file=str(model_file))
    np.testing.assert_allclose(preds, bst.predict(X), rtol=RTOL,
                               atol=1e-6)
    metrics_path = str(out_file) + ".metrics.json"
    assert os.path.exists(metrics_path)
    snap = json.loads(open(metrics_path).read())
    m = snap["models"]["default"]
    assert m["rows"] == 200 and m["shed_count"] == 0
    assert m["buckets_compiled"] <= snap["engine"][
        "max_compilations_per_model"]
