"""sklearn API tests (reference tests/python_package_test/test_sklearn.py)."""

import numpy as np
import pytest

from lightgbm_tpu.sklearn import (LGBMClassifier, LGBMModel, LGBMRanker,
                                  LGBMRegressor)

from conftest import make_binary, make_multiclass, make_ranking, \
    make_regression


class TestRegressor:
    def test_fit_predict(self):
        X, y = make_regression()
        reg = LGBMRegressor(n_estimators=30, num_leaves=15)
        reg.fit(X, y)
        pred = reg.predict(X)
        mse = np.mean((pred - y) ** 2)
        assert mse < np.var(y) * 0.2
        assert reg.n_features_ == X.shape[1]

    def test_eval_set_early_stopping(self):
        X, y = make_regression(n=3000)
        reg = LGBMRegressor(n_estimators=500, learning_rate=0.3)
        reg.fit(X[:2000], y[:2000], eval_set=[(X[2000:], y[2000:])],
                eval_metric="l2", early_stopping_rounds=5)
        assert reg.best_iteration_ < 500
        assert "valid_0" in reg.evals_result_

    def test_feature_importances(self):
        X, y = make_regression()
        reg = LGBMRegressor(n_estimators=10).fit(X, y)
        assert reg.feature_importances_.shape == (X.shape[1],)
        assert reg.feature_importances_.sum() > 0

    def test_params_passthrough(self):
        X, y = make_regression()
        reg = LGBMRegressor(n_estimators=5, reg_alpha=1.0, reg_lambda=2.0,
                            subsample=0.8, subsample_freq=2,
                            colsample_bytree=0.7, min_child_samples=10,
                            random_state=7)
        reg.fit(X, y)
        cfg = reg.booster_.config
        assert cfg.lambda_l1 == 1.0
        assert cfg.lambda_l2 == 2.0
        assert cfg.bagging_fraction == 0.8
        assert cfg.feature_fraction == 0.7
        assert cfg.min_data_in_leaf == 10
        assert cfg.seed == 7

    def test_get_set_params(self):
        reg = LGBMRegressor(num_leaves=7)
        params = reg.get_params()
        assert params["num_leaves"] == 7
        reg.set_params(num_leaves=15)
        assert reg.get_params()["num_leaves"] == 15


class TestClassifier:
    def test_binary(self):
        X, y = make_binary()
        clf = LGBMClassifier(n_estimators=30)
        clf.fit(X, y)
        assert clf.n_classes_ == 2
        proba = clf.predict_proba(X)
        assert proba.shape == (len(y), 2)
        np.testing.assert_allclose(proba.sum(1), 1.0, rtol=1e-6)
        acc = np.mean(clf.predict(X) == y)
        assert acc > 0.9

    def test_multiclass(self):
        X, y = make_multiclass()
        clf = LGBMClassifier(n_estimators=20)
        clf.fit(X, y)
        assert clf.n_classes_ == 4
        assert clf.predict_proba(X).shape == (len(y), 4)
        acc = np.mean(clf.predict(X) == y)
        assert acc > 0.8

    def test_string_labels(self):
        X, y = make_binary(n=1000)
        labels = np.where(y > 0, "spam", "ham")
        clf = LGBMClassifier(n_estimators=10)
        clf.fit(X, labels)
        pred = clf.predict(X)
        assert set(pred) <= {"spam", "ham"}
        assert np.mean(pred == labels) > 0.85

    def test_class_weight_balanced(self):
        X, y = make_binary(n=2000)
        # unbalance the data
        keep = np.where((y == 0) | (np.arange(len(y)) % 5 == 0))[0]
        clf = LGBMClassifier(n_estimators=10, class_weight="balanced")
        clf.fit(X[keep], y[keep])
        assert clf.predict(X).mean() > 0.1  # not collapsed to majority


class TestRanker:
    def test_fit_predict(self):
        X, y, group = make_ranking()
        rk = LGBMRanker(n_estimators=20, num_leaves=15,
                        min_child_samples=5)
        rk.fit(X, y, group=group)
        pred = rk.predict(X)
        assert pred.shape == (len(y),)
        # predictions should correlate with relevance
        assert np.corrcoef(pred, y)[0, 1] > 0.5

    def test_group_required(self):
        X, y, _ = make_ranking()
        with pytest.raises(ValueError):
            LGBMRanker(n_estimators=2).fit(X, y)
