"""tpulint (lightgbm_tpu.analysis) tier-1 tests.

Two halves: (1) the package itself must be clean — zero unsuppressed
findings, the contract that makes the analyzer a guard for every later
PR; (2) fixture files under tests/analysis_fixtures/ prove each rule
fires on a known-bad example at the exact line, that inline
suppressions downgrade without hiding, and that exempt look-alike
idioms stay silent.
"""

import json
import os
import subprocess
import sys

import pytest

import lightgbm_tpu
from lightgbm_tpu.analysis import Analyzer, all_rules

pytestmark = pytest.mark.lint

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "analysis_fixtures")
PACKAGE_DIR = os.path.dirname(os.path.abspath(lightgbm_tpu.__file__))

ALL_RULE_IDS = (
    "COLL001", "COLL002", "COLL003", "COLL004",
    "DTYPE001", "DTYPE002", "FAULT001", "JIT001", "JIT002", "JIT003",
    "JIT004", "LOCK001", "LOCK002", "OBS001", "PALLAS001", "PERF001",
    "REG001", "REG002", "REG003", "REG004", "REG005", "SUP001",
)


def run_on(*relpaths):
    paths = [os.path.join(FIXTURES, p) for p in relpaths]
    return Analyzer().run(paths)


def hits(findings):
    """(rule, line) pairs, suppressed included."""
    return {(f.rule, f.line) for f in findings}


# ----------------------------------------------------------------------
# the tier-1 gate: the package is clean
def test_package_has_zero_unsuppressed_findings():
    findings = Analyzer().run([PACKAGE_DIR])
    active = [f for f in findings if not f.suppressed]
    assert not active, "tpulint violations:\n" + "\n".join(
        f.render() for f in active)


def test_rule_catalogue_complete():
    assert tuple(r.id for r in all_rules()) == ALL_RULE_IDS
    for rule in all_rules():
        assert rule.doc, f"rule {rule.id} has no doc string"
        assert rule.severity in ("error", "warning")


# ----------------------------------------------------------------------
# each rule fires on its known-bad fixture at the exact line
def test_jit_rules_fire():
    findings = run_on("learner/jit_bad.py")
    assert hits(findings) == {
        ("JIT001", 11),   # scalar_leak: lr annotated scalar, not static
        ("JIT001", 18),   # control_flow: depth scalar default
        ("JIT002", 20),   # if depth > 2
        ("JIT002", 22),   # for _ in range(depth)
        ("JIT003", 29),   # float(x.sum())
        ("JIT003", 30),   # np.asarray(x)
        ("JIT003", 31),   # bool(x[0])
        ("JIT003", 32),   # x.max().item()
    }


def test_donation_reuse_rule_fires():
    findings = run_on("learner/donate_bad.py")
    assert hits(findings) == {
        ("JIT004", 17),   # out + score after score donated by keyword
        ("JIT004", 29),   # carry read after positional donation
    }
    # rebind-from-result, attribute receivers, and store-before-read
    # must stay silent
    assert not any("ok_" in (f.message or "") for f in findings)


def test_dtype_rules_fire():
    findings = run_on("learner/dtype_bad.py")
    assert hits(findings) == {
        ("DTYPE001", 9),    # jnp.float64 accumulator
        ("DTYPE001", 10),   # astype("float64")
        ("DTYPE001", 11),   # np.float64
        ("DTYPE002", 12),   # astype(float)
        ("DTYPE002", 13),   # dtype=float kwarg
    }


def test_lock_discipline_fires():
    findings = run_on("lock_bad.py")
    assert hits(findings) == {
        ("LOCK001", 17),    # peek: self._items read outside the lock
        ("LOCK001", 20),    # reset: self._count write outside the lock
    }
    # the `_locked` caller-holds contract stays silent
    assert not any("_drain_locked" in f.message for f in findings)


def test_lock_order_cycle_fires():
    findings = run_on("lock_cycle_bad.py")
    lock2 = [f for f in findings if f.rule == "LOCK002"]
    assert len(lock2) == 1
    assert "Alpha" in lock2[0].message and "Beta" in lock2[0].message


def test_suppression_reports_but_does_not_count():
    findings = run_on("learner/suppressed.py")
    assert hits(findings) == {("JIT003", 10), ("LOCK001", 23)}
    assert all(f.suppressed for f in findings)
    assert not [f for f in findings if not f.suppressed]


def test_pallas_kernel_rule_fires():
    findings = run_on("learner/pallas_bad.py")
    assert hits(findings) == {
        ("PALLAS001", 18),  # pallas_call without grid_spec/in+out_specs
        ("PALLAS001", 26),  # kernel closes over traced `scale`
        ("PALLAS001", 48),  # factory called with traced `scale`
    }
    # the static-factory + operand pattern (clean) must stay silent
    assert not any(f.line > 55 for f in findings)


def test_perf_hot_path_rule_fires():
    # manifest entry points (basename histogram_pallas.py) fire, the
    # nested helper is covered by its enclosing entry, the host-side
    # non-manifest function is exempt, and the oracle-shaped line
    # suppression downgrades without hiding
    findings = run_on("learner/histogram_pallas.py")
    assert hits(findings) == {
        ("PERF001", 12),   # partition_rows: direct argsort
        ("PERF001", 18),   # build_histograms_scatter: nested sweep
        ("PERF001", 30),   # build_histograms_pallas: suppressed oracle
    }
    assert {(f.line, f.suppressed) for f in findings} == {
        (12, False), (18, False), (30, True)}
    assert all(f.rule == "PERF001" for f in findings)


def test_clean_fixture_is_silent():
    # is-None structural branches, .shape/.ndim statics, init-only
    # attrs and the _locked convention must not false-positive
    assert run_on("learner/clean.py") == []


def test_registry_rules_fire():
    findings = run_on("registry_bad")
    got = {(f.rule, os.path.basename(f.path), f.line) for f in findings}
    assert got == {
        ("REG001", "config.py", 1),    # stale doc row 'gamma'
        ("REG001", "config.py", 1),    # wrong total (same anchor line)
        ("REG001", "config.py", 11),   # task alias drift
        ("REG001", "config.py", 13),   # 'alpha' missing doc row
        ("REG001", "config.py", 14),   # alias collides with param name
        ("REG002", "config.py", 11),   # 'predict' unroutable
        ("REG002", "cli.py", 12),      # 'fit' dead branch
        ("REG003", "cli.py", 22),      # cfg.not_a_param
        ("REG004", "cli.py", 21),      # inject('site_zzz') unknown
        ("REG004", "faults.py", 5),    # site_b unwired + undocumented
        ("REG005", "cli.py", 5),       # rogue metric family
    }
    # site_b produces two distinct REG004 findings on the same line
    site_b = [f for f in findings
              if f.rule == "REG004" and "site_b" in f.message]
    assert len(site_b) == 2


def test_fault_coverage_rule_fires():
    findings = run_on("fault_bad")
    assert all(f.rule == "FAULT001" for f in findings)
    sites = {m for f in findings for m in
             ("fused_dispatch", "histogram_build", "collective_psum")
             if m in f.message}
    assert sites == {"fused_dispatch", "histogram_build",
                     "collective_psum"}
    assert len(findings) == 3


def test_observability_bracket_rule_fires():
    # guarded_allgather carries its fault site (FAULT001 quiet) but no
    # span/guard/record_* bracket; checkpoint_agree is covered by
    # delegating to the bracketed wrapper
    findings = run_on("obs_bad")
    assert hits(findings) == {("OBS001", 9)}
    (finding,) = findings
    assert "guarded_allgather" in finding.message


def test_observability_rule_gated_on_flightrec():
    # fixture trees without observability/flightrec.py model packages
    # that predate the flight recorder — OBS001 stays silent there
    assert not [f for f in run_on("fault_bad") if f.rule == "OBS001"]


# ----------------------------------------------------------------------
# SPMD collective-discipline rules (COLL001-COLL004) and the
# stale-suppression self-check (SUP001)
def test_spmd_rules_fire():
    findings = run_on("spmd/coll_bad.py")
    assert hits(findings) == {
        ("COLL001", 15),  # branch_deadlock: psum on one arm only
        ("COLL001", 22),  # loop_deadlock: rank-local trip count
        ("COLL001", 29),  # cond_expr_deadlock: psum(x) if r > 0 else x
        ("COLL002", 34),  # stranded_raise: bare raise, peers allgather
        ("COLL002", 44),  # pr7_bin_parity: the PR-7 bug shape
        ("COLL003", 50),  # ragged_gather: rows[:n] fed to allgather
        ("COLL001", 58),  # resize_epoch_vote: coordinator-only gather
    }


def test_pr7_bug_shape_is_caught():
    # re-introducing the PR-7 stream_bin_parity bug (rank-guarded
    # collective with a bare raise on the other arm) must be caught by
    # COLL001 or COLL002
    findings = run_on("spmd/coll_bad.py")
    pr7 = [f for f in findings
           if f.rule in ("COLL001", "COLL002")
           and "pr7_bin_parity" in f.message]
    assert pr7, "PR-7 bug shape not detected"


def test_spmd_clean_fixture_is_silent():
    # matching arms, agreement sync, participate-then-raise, np.pad to
    # a static wire shape, and rank-uniform config branches/loops
    assert run_on("spmd/coll_clean.py") == []


def test_collective_registry_discovery_fires():
    findings = run_on("spmd_registry_bad/pkg")
    active = {(f.rule, os.path.basename(f.path), f.line)
              for f in findings if not f.suppressed}
    assert active == {("COLL004", "sync.py", 5)}
    # the fixture's REG001 file-suppression is live, so SUP001 is quiet
    assert not any(f.rule == "SUP001" for f in findings)


def test_collective_manifest_covered_in_package():
    # on the real package the manifest itself must be violation-free:
    # no COLL004 finding at all (covered entries + no unregistered
    # collective entry points)
    findings = Analyzer().run([PACKAGE_DIR])
    assert not [f for f in findings if f.rule == "COLL004"]


def test_stale_suppression_self_check():
    findings = run_on("stale_suppress.py")
    sup = {(f.rule, f.line) for f in findings if f.rule == "SUP001"}
    assert sup == {
        ("SUP001", 11),   # disable-file=LOCK002 suppresses nothing
        ("SUP001", 15),   # unknown rule id NOPE123
        ("SUP001", 19),   # disable=JIT003 on a clean line
    }
    # the live LOCK001 suppression is honored, not flagged
    assert {(f.rule, f.line, f.suppressed) for f in findings
            if f.rule == "LOCK001"} == {("LOCK001", 32, True)}


def test_full_package_analysis_wall_time():
    import time
    t0 = time.monotonic()
    Analyzer().run([PACKAGE_DIR])
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, f"tpulint took {elapsed:.1f}s on the package"


# ----------------------------------------------------------------------
# CLI contract: module entry point, exit codes, JSON schema
def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.analysis", *args],
        capture_output=True, text=True, timeout=120)


def test_cli_exit_codes_and_json():
    bad = _run_cli(os.path.join(FIXTURES, "lock_bad.py"),
                   "--format=json")
    assert bad.returncode == 1
    payload = json.loads(bad.stdout)
    assert payload["unsuppressed"] == 2
    assert payload["suppressed"] == 0
    assert {f["rule"] for f in payload["findings"]} == {"LOCK001"}
    for f in payload["findings"]:
        assert set(f) == {"rule", "severity", "path", "line", "message",
                          "suppressed"}

    clean = _run_cli(os.path.join(FIXTURES, "learner", "clean.py"))
    assert clean.returncode == 0
    assert "0 finding(s)" in clean.stdout


def test_cli_sarif_format():
    res = _run_cli(os.path.join(FIXTURES, "lock_bad.py"),
                   "--format=sarif")
    assert res.returncode == 1        # findings still set the exit code
    doc = json.loads(res.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "tpulint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rule_ids == set(ALL_RULE_IDS)
    results = run["results"]
    assert {r["ruleId"] for r in results} == {"LOCK001"}
    assert {r["locations"][0]["physicalLocation"]["region"]["startLine"]
            for r in results} == {17, 20}
    assert all("suppressions" not in r for r in results)

    # suppressed findings carry an inSource suppression record
    sup = _run_cli(os.path.join(FIXTURES, "learner", "suppressed.py"),
                   "--format=sarif")
    assert sup.returncode == 0
    sdoc = json.loads(sup.stdout)
    sresults = sdoc["runs"][0]["results"]
    assert sresults and all(
        r["suppressions"] == [{"kind": "inSource"}] for r in sresults)


def test_cli_list_rules():
    res = _run_cli("--list-rules")
    assert res.returncode == 0
    for rule_id in ALL_RULE_IDS:
        assert rule_id in res.stdout
