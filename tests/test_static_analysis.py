"""tpulint (lightgbm_tpu.analysis) tier-1 tests.

Two halves: (1) the package itself must be clean — zero unsuppressed
findings, the contract that makes the analyzer a guard for every later
PR; (2) fixture files under tests/analysis_fixtures/ prove each rule
fires on a known-bad example at the exact line, that inline
suppressions downgrade without hiding, and that exempt look-alike
idioms stay silent.
"""

import json
import os
import subprocess
import sys

import pytest

import lightgbm_tpu
from lightgbm_tpu.analysis import Analyzer, all_rules

pytestmark = pytest.mark.lint

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "analysis_fixtures")
PACKAGE_DIR = os.path.dirname(os.path.abspath(lightgbm_tpu.__file__))

ALL_RULE_IDS = (
    "COLL001", "COLL002", "COLL003", "COLL004",
    "DTYPE001", "DTYPE002", "FAULT001", "JIT001", "JIT002", "JIT003",
    "JIT004", "LOCK001", "LOCK002", "OBS001", "PALLAS001", "PERF001",
    "REG001", "REG002", "REG003", "REG004", "REG005", "SUP001",
    "TRACE001", "TRACE002", "TRACE003", "TRACE004", "TRACE005",
    "TRACE006",
)


def run_on(*relpaths):
    paths = [os.path.join(FIXTURES, p) for p in relpaths]
    return Analyzer().run(paths)


def hits(findings):
    """(rule, line) pairs, suppressed included."""
    return {(f.rule, f.line) for f in findings}


# one full-package scan shared by every package-level assertion in
# this file (the cold scan builds the trace reports; the wall-time
# test below runs its own warm scan so the <10s budget is meaningful)
@pytest.fixture(scope="module")
def package_findings():
    return Analyzer().run([PACKAGE_DIR])


# ----------------------------------------------------------------------
# the tier-1 gate: the package is clean
def test_package_has_zero_unsuppressed_findings(package_findings):
    active = [f for f in package_findings if not f.suppressed]
    assert not active, "tpulint violations:\n" + "\n".join(
        f.render() for f in active)


def test_rule_catalogue_complete():
    assert tuple(r.id for r in all_rules()) == ALL_RULE_IDS
    for rule in all_rules():
        assert rule.doc, f"rule {rule.id} has no doc string"
        assert rule.severity in ("error", "warning")


# ----------------------------------------------------------------------
# each rule fires on its known-bad fixture at the exact line
def test_jit_rules_fire():
    findings = run_on("learner/jit_bad.py")
    assert hits(findings) == {
        ("JIT001", 11),   # scalar_leak: lr annotated scalar, not static
        ("JIT001", 18),   # control_flow: depth scalar default
        ("JIT002", 20),   # if depth > 2
        ("JIT002", 22),   # for _ in range(depth)
        ("JIT003", 29),   # float(x.sum())
        ("JIT003", 30),   # np.asarray(x)
        ("JIT003", 31),   # bool(x[0])
        ("JIT003", 32),   # x.max().item()
    }


def test_donation_reuse_rule_fires():
    findings = run_on("learner/donate_bad.py")
    assert hits(findings) == {
        ("JIT004", 17),   # out + score after score donated by keyword
        ("JIT004", 29),   # carry read after positional donation
    }
    # rebind-from-result, attribute receivers, and store-before-read
    # must stay silent
    assert not any("ok_" in (f.message or "") for f in findings)


def test_dtype_rules_fire():
    findings = run_on("learner/dtype_bad.py")
    assert hits(findings) == {
        ("DTYPE001", 9),    # jnp.float64 accumulator
        ("DTYPE001", 10),   # astype("float64")
        ("DTYPE001", 11),   # np.float64
        ("DTYPE002", 12),   # astype(float)
        ("DTYPE002", 13),   # dtype=float kwarg
    }


def test_lock_discipline_fires():
    findings = run_on("lock_bad.py")
    assert hits(findings) == {
        ("LOCK001", 17),    # peek: self._items read outside the lock
        ("LOCK001", 20),    # reset: self._count write outside the lock
    }
    # the `_locked` caller-holds contract stays silent
    assert not any("_drain_locked" in f.message for f in findings)


def test_lock_order_cycle_fires():
    findings = run_on("lock_cycle_bad.py")
    lock2 = [f for f in findings if f.rule == "LOCK002"]
    assert len(lock2) == 1
    assert "Alpha" in lock2[0].message and "Beta" in lock2[0].message


def test_suppression_reports_but_does_not_count():
    findings = run_on("learner/suppressed.py")
    assert hits(findings) == {("JIT003", 10), ("LOCK001", 23)}
    assert all(f.suppressed for f in findings)
    assert not [f for f in findings if not f.suppressed]


def test_pallas_kernel_rule_fires():
    findings = run_on("learner/pallas_bad.py")
    assert hits(findings) == {
        ("PALLAS001", 18),  # pallas_call without grid_spec/in+out_specs
        ("PALLAS001", 26),  # kernel closes over traced `scale`
        ("PALLAS001", 48),  # factory called with traced `scale`
    }
    # the static-factory + operand pattern (clean) must stay silent
    assert not any(f.line > 55 for f in findings)


def test_perf_hot_path_rule_fires():
    # manifest entry points (basename histogram_pallas.py) fire, the
    # nested helper is covered by its enclosing entry, the host-side
    # non-manifest function is exempt, and the oracle-shaped line
    # suppression downgrades without hiding
    findings = run_on("learner/histogram_pallas.py")
    assert hits(findings) == {
        ("PERF001", 12),   # partition_rows: direct argsort
        ("PERF001", 18),   # build_histograms_scatter: nested sweep
        ("PERF001", 30),   # build_histograms_pallas: suppressed oracle
    }
    assert {(f.line, f.suppressed) for f in findings} == {
        (12, False), (18, False), (30, True)}
    assert all(f.rule == "PERF001" for f in findings)


def test_clean_fixture_is_silent():
    # is-None structural branches, .shape/.ndim statics, init-only
    # attrs and the _locked convention must not false-positive
    assert run_on("learner/clean.py") == []


def test_registry_rules_fire():
    findings = run_on("registry_bad")
    got = {(f.rule, os.path.basename(f.path), f.line) for f in findings}
    assert got == {
        ("REG001", "config.py", 1),    # stale doc row 'gamma'
        ("REG001", "config.py", 1),    # wrong total (same anchor line)
        ("REG001", "config.py", 11),   # task alias drift
        ("REG001", "config.py", 13),   # 'alpha' missing doc row
        ("REG001", "config.py", 14),   # alias collides with param name
        ("REG002", "config.py", 11),   # 'predict' unroutable
        ("REG002", "cli.py", 12),      # 'fit' dead branch
        ("REG003", "cli.py", 22),      # cfg.not_a_param
        ("REG004", "cli.py", 21),      # inject('site_zzz') unknown
        ("REG004", "faults.py", 5),    # site_b unwired + undocumented
        ("REG005", "cli.py", 5),       # rogue metric family
    }
    # site_b produces two distinct REG004 findings on the same line
    site_b = [f for f in findings
              if f.rule == "REG004" and "site_b" in f.message]
    assert len(site_b) == 2


def test_fault_coverage_rule_fires():
    findings = run_on("fault_bad")
    assert all(f.rule == "FAULT001" for f in findings)
    sites = {m for f in findings for m in
             ("fused_dispatch", "histogram_build", "collective_psum")
             if m in f.message}
    assert sites == {"fused_dispatch", "histogram_build",
                     "collective_psum"}
    assert len(findings) == 3


def test_observability_bracket_rule_fires():
    # guarded_allgather carries its fault site (FAULT001 quiet) but no
    # span/guard/record_* bracket; checkpoint_agree is covered by
    # delegating to the bracketed wrapper
    findings = run_on("obs_bad")
    assert hits(findings) == {("OBS001", 9)}
    (finding,) = findings
    assert "guarded_allgather" in finding.message


def test_observability_rule_gated_on_flightrec():
    # fixture trees without observability/flightrec.py model packages
    # that predate the flight recorder — OBS001 stays silent there
    assert not [f for f in run_on("fault_bad") if f.rule == "OBS001"]


# ----------------------------------------------------------------------
# SPMD collective-discipline rules (COLL001-COLL004) and the
# stale-suppression self-check (SUP001)
def test_spmd_rules_fire():
    findings = run_on("spmd/coll_bad.py")
    assert hits(findings) == {
        ("COLL001", 15),  # branch_deadlock: psum on one arm only
        ("COLL001", 22),  # loop_deadlock: rank-local trip count
        ("COLL001", 29),  # cond_expr_deadlock: psum(x) if r > 0 else x
        ("COLL002", 34),  # stranded_raise: bare raise, peers allgather
        ("COLL002", 44),  # pr7_bin_parity: the PR-7 bug shape
        ("COLL003", 50),  # ragged_gather: rows[:n] fed to allgather
        ("COLL001", 58),  # resize_epoch_vote: coordinator-only gather
    }


def test_pr7_bug_shape_is_caught():
    # re-introducing the PR-7 stream_bin_parity bug (rank-guarded
    # collective with a bare raise on the other arm) must be caught by
    # COLL001 or COLL002
    findings = run_on("spmd/coll_bad.py")
    pr7 = [f for f in findings
           if f.rule in ("COLL001", "COLL002")
           and "pr7_bin_parity" in f.message]
    assert pr7, "PR-7 bug shape not detected"


def test_spmd_clean_fixture_is_silent():
    # matching arms, agreement sync, participate-then-raise, np.pad to
    # a static wire shape, and rank-uniform config branches/loops
    assert run_on("spmd/coll_clean.py") == []


def test_collective_registry_discovery_fires():
    findings = run_on("spmd_registry_bad/pkg")
    active = {(f.rule, os.path.basename(f.path), f.line)
              for f in findings if not f.suppressed}
    assert active == {("COLL004", "sync.py", 5)}
    # the fixture's REG001 file-suppression is live, so SUP001 is quiet
    assert not any(f.rule == "SUP001" for f in findings)


def test_collective_manifest_covered_in_package(package_findings):
    # on the real package the manifest itself must be violation-free:
    # no COLL004 finding at all (covered entries + no unregistered
    # collective entry points)
    assert not [f for f in package_findings if f.rule == "COLL004"]


def test_stale_suppression_self_check():
    findings = run_on("stale_suppress.py")
    sup = {(f.rule, f.line) for f in findings if f.rule == "SUP001"}
    assert sup == {
        ("SUP001", 11),   # disable-file=LOCK002 suppresses nothing
        ("SUP001", 15),   # unknown rule id NOPE123
        ("SUP001", 19),   # disable=JIT003 on a clean line
    }
    # the live LOCK001 suppression is honored, not flagged
    assert {(f.rule, f.line, f.suppressed) for f in findings
            if f.rule == "LOCK001"} == {("LOCK001", 32, True)}


# ----------------------------------------------------------------------
# TRACE rule family: contracts checked on the traced program (jaxpr),
# driven by a machine-checked manifest
def test_trace_rules_fire():
    findings = run_on("trace_bad")
    assert hits(findings) == {
        ("TRACE001", 94),   # sorting_entry: jnp.sort in the jaxpr
        ("TRACE002", 97),   # f64_entry: strong float64 under x64
        ("TRACE003", 100),  # callback_entry: debug_callback primitive
        ("TRACE004", 103),  # dead_donation_entry: donation unusable
        ("TRACE005", 107),  # baked_scalar_entry: static arg re-traces
        ("TRACE006", 1),    # manifest-level coverage findings
    }
    cov = [f for f in findings if f.rule == "TRACE006"]
    assert len(cov) == 2
    msgs = " | ".join(f.message for f in cov)
    assert "fused_dispatch" in msgs      # uncovered dispatch row
    assert "old_entry" in msgs           # stale waiver


def test_trace_clean_fixture_is_silent():
    # donation consumed, traced scalar stable across retraces, x64
    # trace clean, dispatch row covered, no waivers
    assert run_on("trace_clean") == []


def test_trace_manifest_covers_dispatch_sites():
    # the production manifest must cover or explicitly waive every
    # device-dispatch row — TRACE006 enforces this at lint time, this
    # test pins it structurally so a new dispatch site fails fast
    from lightgbm_tpu.analysis.rules_faults import DISPATCH_MANIFEST
    from lightgbm_tpu.analysis.tracecheck import TRACE_MANIFEST, WAIVERS
    covered = {c for e in TRACE_MANIFEST for c in e.covers}
    for row in DISPATCH_MANIFEST:
        key = tuple(row)
        assert key in covered or key in WAIVERS, \
            f"dispatch row {key} neither traced nor waived"
    # waivers must carry a reason and not shadow a covered row
    for key, reason in WAIVERS.items():
        assert reason.strip()
        assert key not in covered


# ----------------------------------------------------------------------
# interprocedural engine: findings that require the project call graph
def test_interproc_findings_fire_across_modules():
    findings = run_on("interproc_bad")
    got = {(f.rule, os.path.basename(f.path), f.line) for f in findings}
    assert got == {
        ("JIT003", "jit_sync.py", 12),  # float() two modules away
        ("COLL001", "work.py", 11),     # psum hidden inside the callee
        ("LOCK001", "ring.py", 15),     # _locked delegate, no lock held
    }
    # findings must name the callee and its definition site
    jit = next(f for f in findings if f.rule == "JIT003")
    assert "to_python_scalar" in jit.message
    assert "convert.py" in jit.message
    lock = next(f for f in findings if f.rule == "LOCK001")
    assert "append_locked" in lock.message
    assert "store.py" in lock.message


def test_interproc_findings_need_the_callgraph():
    # the same fixtures are provably invisible to the intraprocedural
    # engine: each file is clean in isolation
    bad = os.path.join(FIXTURES, "interproc_bad")
    assert Analyzer(interproc=False).run([bad]) == []


def test_interproc_clean_fixture_is_silent():
    # lock held around the delegate, shape-only helper, rank-uniform
    # collective call — the call graph must not over-taint these
    assert run_on("interproc_clean") == []


# ----------------------------------------------------------------------
# incremental cache: content-hash keys, dependent invalidation
def test_lint_cache_roundtrip_and_invalidation(tmp_path):
    from lightgbm_tpu.analysis.cache import LintCache
    src = tmp_path / "mod.py"
    dep = tmp_path / "helper.py"
    src.write_text("x = 1\n")
    dep.write_text("y = 2\n")

    cache = LintCache(str(tmp_path))
    key = cache.file_key(str(src), [str(dep)], interproc=True)
    assert cache.get_file_findings(key) is None
    cache.put_file_findings(key, [{"rule": "JIT003", "line": 3}])
    # a fresh instance (no memoized hashes) computes the same key and
    # reads the stored payload back
    fresh = LintCache(str(tmp_path))
    assert fresh.file_key(str(src), [str(dep)], interproc=True) == key
    assert fresh.get_file_findings(key) == [{"rule": "JIT003",
                                             "line": 3}]
    # toggling interproc changes the key
    assert cache.file_key(str(src), [str(dep)],
                          interproc=False) != key
    # editing only the *dependency* invalidates the dependent file
    dep.write_text("y = 3\n")
    assert LintCache(str(tmp_path)).file_key(
        str(src), [str(dep)], interproc=True) != key


def test_cache_engages_for_package_scans_only(package_findings):
    # the shared package scan (the fixture) ran with cache on
    from lightgbm_tpu.analysis.cache import CACHE_DIR_NAME
    repo_root = os.path.dirname(PACKAGE_DIR)
    assert os.path.isdir(os.path.join(repo_root, CACHE_DIR_NAME))
    # fixture scans must never sprinkle cache directories around
    assert not os.path.exists(os.path.join(FIXTURES, CACHE_DIR_NAME))


def test_full_package_analysis_wall_time(package_findings):
    # warm-cache scan (the shared module fixture paid the cold trace
    # builds): the per-commit lint loop must stay under the budget
    import time
    t0 = time.monotonic()
    Analyzer().run([PACKAGE_DIR])
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, f"tpulint took {elapsed:.1f}s on the package"


# ----------------------------------------------------------------------
# CLI contract: module entry point, exit codes, JSON schema
def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.analysis", *args],
        capture_output=True, text=True, timeout=120)


def test_cli_exit_codes_and_json():
    # --no-cache rides along: accepted, and findings are unchanged
    bad = _run_cli(os.path.join(FIXTURES, "lock_bad.py"), "--no-cache",
                   "--format=json")
    assert bad.returncode == 1
    payload = json.loads(bad.stdout)
    assert payload["unsuppressed"] == 2
    assert payload["suppressed"] == 0
    assert {f["rule"] for f in payload["findings"]} == {"LOCK001"}
    for f in payload["findings"]:
        assert set(f) == {"rule", "severity", "path", "line", "message",
                          "suppressed"}

    clean = _run_cli(os.path.join(FIXTURES, "learner", "clean.py"))
    assert clean.returncode == 0
    assert "0 finding(s)" in clean.stdout


def test_cli_sarif_format():
    res = _run_cli(os.path.join(FIXTURES, "lock_bad.py"),
                   "--format=sarif")
    assert res.returncode == 1        # findings still set the exit code
    doc = json.loads(res.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "tpulint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rule_ids == set(ALL_RULE_IDS)
    results = run["results"]
    assert {r["ruleId"] for r in results} == {"LOCK001"}
    assert {r["locations"][0]["physicalLocation"]["region"]["startLine"]
            for r in results} == {17, 20}
    assert all("suppressions" not in r for r in results)

    # suppressed findings carry an inSource suppression record
    sup = _run_cli(os.path.join(FIXTURES, "learner", "suppressed.py"),
                   "--format=sarif")
    assert sup.returncode == 0
    sdoc = json.loads(sup.stdout)
    sresults = sdoc["runs"][0]["results"]
    assert sresults and all(
        r["suppressions"] == [{"kind": "inSource"}] for r in sresults)


def test_cli_list_rules():
    res = _run_cli("--list-rules")
    assert res.returncode == 0
    for rule_id in ALL_RULE_IDS:
        assert rule_id in res.stdout


def test_cli_no_interproc_flag():
    # the default-on behaviour is pinned in-process
    # (test_interproc_findings_fire_across_modules); here the flag must
    # drop the cross-module findings through the CLI
    off = _run_cli(os.path.join(FIXTURES, "interproc_bad"),
                   "--no-interproc", "--format=json")
    assert off.returncode == 0
    assert json.loads(off.stdout)["unsuppressed"] == 0
