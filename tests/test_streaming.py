"""Out-of-core streaming ingestion tests (docs/Streaming.md).

Parity anchor: while the reservoir has seen no more rows than its
capacity it holds ALL rows in stream order, and the loader hands that
sample to `find_bin_mappers` with the same `sample_cnt`/`seed` the
in-memory `from_raw` path uses — so with `stream_sample_rows >= N`
streamed training is byte-identical to in-memory, model.txt included.

Mapper equality is asserted via `json.dumps(to_dict())` strings, never
`==` on the dicts: boundary lists contain NaN and `nan != nan` makes
plain equality report spurious mismatches.

Markers: `streaming` (this tier, `make stream`); the 10M-row
bounded-memory smoke is additionally `slow`.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import LightGBMError
from lightgbm_tpu.data import BinnedDataset, Metadata
from lightgbm_tpu.reliability import InjectedFault, faults
from lightgbm_tpu.streaming import (ArraySource, ChunkSource, CSVSource,
                                    NpySource, ReservoirSketch,
                                    build_streamed_dataset, source_from_path)

from conftest import make_binary, make_multiclass, make_regression

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

pytestmark = pytest.mark.streaming

def mapper_json(binned: BinnedDataset) -> str:
    return json.dumps([m.to_dict() for m in binned.mappers])


def from_raw_ref(X, y, **kw):
    return BinnedDataset.from_raw(
        np.asarray(X, np.float64),
        Metadata(len(X), label=np.asarray(y, np.float32)), **kw)


def assert_binned_equal(a: BinnedDataset, b: BinnedDataset):
    assert mapper_json(a) == mapper_json(b)
    assert list(a.used_features) == list(b.used_features)
    assert a.bins.dtype == b.bins.dtype
    assert np.array_equal(a.bins, b.bins)


def write_csv(path, X, y, delimiter=","):
    np.savetxt(path, np.column_stack([y, X]), delimiter=delimiter)


# ---------------------------------------------------------------- sketch

class TestReservoirSketch:
    def test_exact_below_capacity(self, rng):
        X = rng.randn(500, 4)
        sk = ReservoirSketch(4, capacity=1000, seed=3)
        for lo in range(0, 500, 64):
            sk.add_chunk(X[lo:lo + 64])
        assert sk.is_exact and sk.sample_rows == 500
        # all rows, in stream order — the parity anchor
        assert np.array_equal(sk.sample(), X)

    def test_overflow_draws_from_population(self, rng):
        X = rng.randn(5000, 3)
        sk = ReservoirSketch(3, capacity=256, seed=3)
        sk.add_chunk(X)
        assert not sk.is_exact and sk.sample_rows == 256
        s = sk.sample()
        # every sampled row exists in the population
        pop = {r.tobytes() for r in X}
        assert all(r.tobytes() in pop for r in s)

    def test_algorithm_r_uniformity(self):
        # stream [0..n): inclusion should not favour early/late rows —
        # the mean of surviving indices stays near n/2 across seeds
        n, cap = 4000, 400
        col = np.arange(n, dtype=np.float64).reshape(-1, 1)
        means = []
        for seed in range(8):
            sk = ReservoirSketch(1, capacity=cap, seed=seed)
            for lo in range(0, n, 333):
                sk.add_chunk(col[lo:lo + 333])
            means.append(sk.sample().mean())
        # sd of a uniform-index mean is ~ n/sqrt(12*cap) ~ 58; the
        # across-seed average tightens by sqrt(8)
        assert abs(np.mean(means) - (n - 1) / 2) < 150

    def test_state_roundtrip_mid_stream(self, rng):
        X = rng.randn(3000, 5)
        a = ReservoirSketch(5, capacity=300, seed=9)
        b = ReservoirSketch(5, capacity=300, seed=9)
        for lo in range(0, 1500, 250):
            a.add_chunk(X[lo:lo + 250])
            b.add_chunk(X[lo:lo + 250])
        b = ReservoirSketch.from_state(b.state_dict())  # suspend/resume
        for lo in range(1500, 3000, 250):
            a.add_chunk(X[lo:lo + 250])
            b.add_chunk(X[lo:lo + 250])
        assert np.array_equal(a.sample(), b.sample())

    def test_merge_exact(self, rng):
        X = rng.randn(400, 2)
        a = ReservoirSketch(2, capacity=1000, seed=1)
        b = ReservoirSketch(2, capacity=1000, seed=2)
        a.add_chunk(X[:150])
        b.add_chunk(X[150:])
        m = a.merge(b)
        assert m.is_exact and np.array_equal(m.sample(), X)


# ------------------------------------------------------- sources + synth

class TestSources:
    def test_array_source_zero_copy(self, rng):
        X = rng.randn(1000, 6).astype(np.float32)
        src = ArraySource(X, chunk_rows=128)
        chunks = list(src.chunks())
        assert sum(c[0].shape[0] for c in chunks) == 1000
        assert chunks[0][0].base is X  # view, not a copy

    def test_csv_source_roundtrip(self, tmp_path, rng):
        X = rng.randn(777, 5)
        y = (rng.rand(777) > 0.5).astype(np.float64)
        p = tmp_path / "d.csv"
        write_csv(p, X, y)
        src = CSVSource(str(p), chunk_rows=100)
        xs, ys = zip(*src.chunks())
        assert np.allclose(np.concatenate(xs), X)
        assert np.array_equal(np.concatenate(ys), y)
        assert src.num_rows == 777

    def test_npy_source_memmap(self, tmp_path, rng):
        X = rng.randn(300, 4).astype(np.float32)
        p = tmp_path / "d.npy"
        np.save(p, X)
        src = source_from_path(str(p), chunk_rows=64)
        assert isinstance(src, NpySource)
        assert np.array_equal(
            np.concatenate([c[0] for c in src.chunks()]), X)

    def test_parquet_gated(self, tmp_path):
        pa = pytest.importorskip("pyarrow", reason="pyarrow not installed")
        import pyarrow.parquet as pq
        rng = np.random.RandomState(0)
        X = rng.randn(100, 3)
        y = (rng.rand(100) > 0.5).astype(np.float32)
        t = pa.table({"target": y, "a": X[:, 0], "b": X[:, 1],
                      "c": X[:, 2]})
        p = tmp_path / "d.parquet"
        pq.write_table(t, str(p))
        # the configured label_column index resolves against the schema
        src = source_from_path(str(p), chunk_rows=32, label_col=0)
        assert src.label_col == "target" and src.num_features == 3
        xs, ys = zip(*src.chunks())
        assert np.allclose(np.concatenate(xs), X)
        assert np.allclose(np.concatenate(ys), y)
        with pytest.raises(ValueError, match="not found"):
            source_from_path(str(p), label_col="name:label")

    def test_parquet_label_resolution(self):
        # pure schema logic — runs without pyarrow
        from lightgbm_tpu.streaming.sources import ParquetSource
        names = ["f0", "target", "f1"]
        r = ParquetSource._resolve_label
        assert r(None, names) is None
        assert r(1, names) == "target"
        assert r("1", names) == "target"
        assert r("name:target", names) == "target"
        assert r("target", names) == "target"
        with pytest.raises(ValueError, match="not found"):
            r("name:label", names)   # the old hardcoded default
        with pytest.raises(ValueError, match="out of range"):
            r(7, names)

    def test_csv_name_label_column_rejected(self, tmp_path, rng):
        p = tmp_path / "d.csv"
        write_csv(p, rng.randn(10, 3), np.zeros(10))
        with pytest.raises(ValueError, match="header parsing"):
            source_from_path(str(p), label_col="name:target")

    def test_synth_chunk_layout_invariance(self):
        from helpers.synth import SynthSource, synth_chunk
        X, y = synth_chunk(0, 900, 11, seed=5)
        for cuts in ([900], [1, 899], [450, 449, 1], [300] * 3):
            lo, xs, ys = 0, [], []
            for n in cuts:
                cx, cy = synth_chunk(lo, n, 11, seed=5)
                xs.append(cx); ys.append(cy); lo += n
            assert np.array_equal(np.concatenate(xs), X)
            assert np.array_equal(np.concatenate(ys), y)
        src = SynthSource(rows=900, cols=11, chunk_rows=137, seed=5)
        assert np.array_equal(
            np.concatenate([c[0] for c in src.chunks()]), X)


# ------------------------------------------------- mapper / bin parity

class TestBinParity:
    def test_covering_sample_bit_parity(self, rng):
        X, y = make_binary(n=1500, f=8, seed=3)
        ref = from_raw_ref(X, y)
        got = build_streamed_dataset(
            ArraySource(np.asarray(X), chunk_rows=200),
            label=np.asarray(y, np.float32), sample_rows=1500)
        assert_binned_equal(ref, got)
        assert got.stream_stats.exact

    def test_csv_matches_in_memory(self, tmp_path, rng):
        X, y = make_binary(n=1200, f=6, seed=7)
        p = tmp_path / "d.csv"
        write_csv(p, X, y)
        ref = from_raw_ref(X, y)
        got = build_streamed_dataset(CSVSource(str(p), chunk_rows=171),
                                     sample_rows=1200)
        assert_binned_equal(ref, got)
        assert np.allclose(got.metadata.label, y)

    @pytest.mark.parametrize("layout", ["nan_heavy", "const_split",
                                        "tie_boundary", "single_row_tail"])
    def test_adversarial_chunk_layouts(self, layout, rng):
        n = 1000
        X = rng.randn(n, 4)
        if layout == "nan_heavy":
            X[:300, 1] = np.nan          # whole early chunks all-NaN
            X[rng.rand(n) < 0.3, 2] = np.nan
            chunk = 150
        elif layout == "const_split":
            X[:, 1] = 3.25               # constant feature crosses chunks
            X[:500, 2] = -1.0            # constant only in the first half
            chunk = 250
        elif layout == "tie_boundary":
            X[:, 1] = np.repeat(np.arange(10.0), n // 10)  # massive ties
            chunk = 100                  # boundary lands inside tie runs
        else:
            chunk = 999                  # final chunk has exactly 1 row
        y = (rng.rand(n) > 0.5).astype(np.float32)
        ref = from_raw_ref(X, y)
        got = build_streamed_dataset(ArraySource(X, chunk_rows=chunk),
                                     label=y, sample_rows=n)
        assert_binned_equal(ref, got)

    def test_sketch_route_non_covering_is_sane(self, rng):
        # undersized reservoir: approximate, but bins stay valid and
        # every feature's bin count matches the mapper contract
        X, y = make_binary(n=4000, f=6, seed=1)
        got = build_streamed_dataset(
            PureStream(X, y, chunk_rows=500),
            sample_rows=512)
        assert not got.stream_stats.exact
        assert got.bins.shape == (4000, len(got.used_features))
        for j, m in enumerate(got.mappers):
            assert got.bins[:, j].max() < m.num_bin

    def test_bin_parity_flag_raises_when_not_covering(self, rng):
        X, y = make_binary(n=2000, f=4, seed=2)
        with pytest.raises(LightGBMError, match="stream_bin_parity"):
            build_streamed_dataset(
                PureStream(X, y, chunk_rows=400),
                sample_rows=100, bin_parity=True)


class PureStream(ChunkSource):
    """Unsized pure-stream wrapper (`array` None, `num_rows` None like a
    first CSV pass) so tests can force the sketch path without disk."""

    has_label = True

    def __init__(self, X, y, chunk_rows):
        super().__init__(chunk_rows)
        self._X = np.asarray(X, np.float64)
        self._y = np.asarray(y, np.float64)
        self.num_features = int(self._X.shape[1])

    def chunks(self, start_chunk=0):
        step = self.chunk_rows
        for lo in range(start_chunk * step, len(self._X), step):
            yield self._X[lo:lo + step], self._y[lo:lo + step]


# ------------------------------------------- multihost mapper sync

class TestMapperSync:
    """Pure streams under num_machines>1 must derive bin boundaries
    collectively: per-rank local boundaries + a histogram psum silently
    trains a wrong model (REVIEW: basic.py only synced array-backed
    sources)."""

    def test_mapper_sync_replaces_local_find(self):
        from lightgbm_tpu.binning import find_bin_mappers
        X, y = make_binary(n=1200, f=5, seed=9)
        calls = []

        def sync(sample):
            calls.append(sample.shape)
            return find_bin_mappers(np.asarray(sample))

        got = build_streamed_dataset(PureStream(X, y, chunk_rows=300),
                                     sample_rows=1200, mapper_sync=sync)
        # the hook received the full covering sketch sample and its
        # mappers are the ones the dataset was binned with
        assert calls == [(1200, 5)]
        assert_binned_equal(from_raw_ref(X, y), got)

    def test_pure_stream_dataset_requests_sync_hook(self, tmp_path,
                                                    monkeypatch):
        # _construct_streamed must ask for the collective on every
        # pure-stream construct (it returns None single-process); the
        # array-backed path keeps using _distributed_bin_mappers
        import lightgbm_tpu.basic as basic
        X, y = make_binary(n=900, f=4, seed=5)
        p = tmp_path / "d.csv"
        write_csv(p, X, y)
        requested = []
        real = basic._streaming_mapper_sync

        def spy(cfg, cat):
            requested.append(True)
            return real(cfg, cat)

        monkeypatch.setattr(basic, "_streaming_mapper_sync", spy)
        params = {"stream_input": True, "stream_chunk_rows": 200,
                  "stream_sample_rows": 900, "verbosity": -1}
        ds = lgb.Dataset(str(p), params=params).construct()
        assert requested
        assert_binned_equal(from_raw_ref(X, y), ds._binned)

    def test_empty_stream_joins_collective_before_raise(self):
        # a rank whose partition yields no chunks hands None to the
        # sync — joining the agreement collective — BEFORE raising, so
        # peers fail identically instead of hanging in the allgather
        # (tpulint COLL002, the PR-7 bug shape)
        calls = []

        def sync(sample):
            calls.append(sample)
            if sample is None:
                raise LightGBMError("peer rank produced no sample rows")
            return []

        empty = PureStream(np.empty((0, 3)), np.empty(0), chunk_rows=64)
        with pytest.raises(LightGBMError, match="no sample rows"):
            build_streamed_dataset(empty, sample_rows=64,
                                   mapper_sync=sync)
        assert calls == [None]

    def test_empty_stream_without_sync_raises_locally(self):
        # single-process: no collective to join, plain loud failure
        empty = PureStream(np.empty((0, 3)), np.empty(0), chunk_rows=64)
        with pytest.raises(LightGBMError, match="yielded no chunks"):
            build_streamed_dataset(empty, sample_rows=64)

    def test_allgather_agreement_flags_empty_rank(self, monkeypatch):
        # _allgather_find_mappers gathers one ok-flag per rank before
        # any rows ship: a None sample aborts every rank with the same
        # error, and no row gather ever starts
        import lightgbm_tpu.basic as basic
        from jax.experimental import multihost_utils
        from lightgbm_tpu.config import Config
        gathered = []

        def fake_allgather(tree):
            # guarded_allgather ships (payload, wall-clock stamp,
            # membership epoch): the real process_allgather maps over
            # the pytree
            arr, wall, epoch = tree
            gathered.append(np.asarray(arr))
            return (np.asarray(arr)[None], np.asarray(wall)[None],
                    np.asarray(epoch)[None])

        monkeypatch.setattr(multihost_utils, "process_allgather",
                            fake_allgather)
        with pytest.raises(LightGBMError, match="no sample rows"):
            basic._allgather_find_mappers(None, Config(), None)
        assert len(gathered) == 1          # only the agreement flag
        assert gathered[0].shape == ()

    def test_allgather_agreement_then_rows(self, monkeypatch):
        # healthy path: agreement flag first, then sizes + padded rows;
        # the derived mappers match the local reference bit-for-bit
        import lightgbm_tpu.basic as basic
        from jax.experimental import multihost_utils
        from lightgbm_tpu.binning import find_bin_mappers
        from lightgbm_tpu.config import Config
        X, _ = make_binary(n=300, f=4, seed=3)
        Xd = np.asarray(X, np.float64)
        monkeypatch.setattr(
            multihost_utils, "process_allgather",
            lambda tree: tuple(np.asarray(x)[None] for x in tree))
        cfg = Config({"bin_construct_sample_cnt": 300})
        got = basic._allgather_find_mappers(Xd, cfg, None)
        ref = find_bin_mappers(
            Xd, max_bin=cfg.max_bin,
            min_data_in_bin=cfg.min_data_in_bin, sample_cnt=300,
            use_missing=cfg.use_missing,
            zero_as_missing=cfg.zero_as_missing,
            categorical_features=None, seed=cfg.data_random_seed)
        assert [m.to_dict() for m in got] == [m.to_dict() for m in ref]

    def test_bin_parity_rejected_under_multihost(self):
        # per-rank coverage failures would strand peers inside the
        # mapper collective, so the combination fails fast on all ranks
        X, y = make_binary(n=500, f=3, seed=2)
        with pytest.raises(LightGBMError, match="num_machines=1"):
            build_streamed_dataset(PureStream(X, y, chunk_rows=100),
                                   sample_rows=500, bin_parity=True,
                                   mapper_sync=lambda s: [])

    def test_post_sketch_state_discarded_under_sync(self, tmp_path):
        # resuming past the collective on one rank while peers enter it
        # would deadlock the allgather: "bin"-phase state is only
        # trusted single-process
        from lightgbm_tpu.binning import find_bin_mappers
        from lightgbm_tpu.streaming.loader import _save_stream_state
        X, y = make_binary(n=800, f=4, seed=8)
        ck = tmp_path / "ck"
        _save_stream_state(str(ck), {
            "phase": "bin", "next_chunk": 0, "num_features": 4,
            "rows": 800, "sample_rows": 800, "exact": True,
            "mappers": []},
            {"labels": np.zeros(800, np.float32)})
        calls = []

        def sync(sample):
            calls.append(sample.shape)
            return find_bin_mappers(np.asarray(sample))

        got = build_streamed_dataset(PureStream(X, y, chunk_rows=200),
                                     sample_rows=800, mapper_sync=sync,
                                     checkpoint_dir=str(ck))
        assert calls == [(800, 4)]   # pass 1 re-ran through the hook
        assert_binned_equal(from_raw_ref(X, y), got)


# ------------------------------------------------ model.txt byte parity

class TestModelByteParity:
    @pytest.mark.parametrize("task", ["regression", "binary", "multiclass"])
    def test_streamed_model_identical(self, task, tmp_path):
        if task == "regression":
            X, y = make_regression(n=1100, f=7, seed=11)
            params = {"objective": "regression", "metric": "l2"}
        elif task == "binary":
            X, y = make_binary(n=1100, f=7, seed=11)
            params = {"objective": "binary"}
        else:
            X, y = make_multiclass(n=1200, f=7, k=3, seed=11)
            params = {"objective": "multiclass", "num_class": 3}
        # stream_input in BOTH param sets: the ndarray path ignores it,
        # but model.txt dumps every param, and the tree bytes are what
        # this test is about
        params.update({"num_leaves": 15, "verbosity": -1,
                       "deterministic": True, "stream_input": True,
                       "stream_chunk_rows": 190,
                       "stream_sample_rows": len(X)})  # covering sample
        p = tmp_path / "train.csv"
        write_csv(p, X, y)

        mem = lgb.train(params, lgb.Dataset(
            np.asarray(X), label=np.asarray(y, np.float32),
            params=params), num_boost_round=12)
        streamed = lgb.train(params, lgb.Dataset(
            str(p), params=params), num_boost_round=12)
        assert streamed.model_to_string() == mem.model_to_string()


# ------------------------------------------- in-memory spine (satellite)

class TestInMemorySpine:
    def test_numpy_routes_through_chunksource(self, rng):
        X, y = make_binary(n=1500, f=8, seed=4)
        ds = lgb.Dataset(np.asarray(X),
                         label=np.asarray(y, np.float32)).construct()
        st = getattr(ds._binned, "stream_stats", None)
        assert st is not None and st.exact and st.rows == 1500
        assert_binned_equal(from_raw_ref(X, y), ds._binned)

    def test_f32_input_not_upcast_to_f64_copy(self, rng):
        X = rng.randn(2000, 8).astype(np.float32)
        y = (rng.rand(2000) > 0.5).astype(np.float32)
        ds = lgb.Dataset(X, label=y).construct()
        ref = BinnedDataset.from_raw(X, Metadata(2000, label=y))
        assert_binned_equal(ref, ds._binned)

    def test_peak_rss_no_full_f64_copy(self):
        # the old `_to_2d_float` path materialized a full float64 copy
        # of the 1M x 28 f32 bench matrix (+224 MB). The ChunkSource
        # spine bins from zero-copy views; construct overhead must stay
        # well under that copy. Subprocess so ru_maxrss is ours alone.
        code = textwrap.dedent("""
            import resource, sys
            import numpy as np
            import lightgbm_tpu as lgb
            rss = lambda: resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            X = np.random.RandomState(0).randn(1_000_000, 28)
            X = X.astype(np.float32)
            y = (X[:, 0] > 0).astype(np.float32)
            lgb.Dataset(X[:1000], label=y[:1000]).construct()  # warm code
            before = rss()
            lgb.Dataset(X, label=y).construct()
            delta_mb = (rss() - before) / 1024.0
            print(delta_mb)
            sys.exit(0 if delta_mb < 150.0 else 17)
        """)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, (
            f"construct peak-RSS regression: +{r.stdout.strip()} MB "
            f"(f64 full copy is +224 MB)\n{r.stderr[-2000:]}")


# --------------------------------------------- checkpoint / resume

class TestCheckpointResume:
    def _params(self, tmp_path, n):
        return {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                "deterministic": True, "stream_input": True,
                "stream_chunk_rows": 200, "stream_sample_rows": n,
                "checkpoint_dir": str(tmp_path / "ckpt")}

    def test_mid_stream_kill_resume_byte_identity(self, tmp_path):
        n = 1400
        X, y = make_binary(n=n, f=6, seed=13)
        p = tmp_path / "train.csv"
        write_csv(p, X, y)
        params = self._params(tmp_path, n)
        (tmp_path / "ckpt").mkdir()

        # uninterrupted reference (fresh dir so no state is picked up)
        ref_params = dict(params, checkpoint_dir=str(tmp_path / "ref"))
        (tmp_path / "ref").mkdir()
        ref = lgb.train(ref_params, lgb.Dataset(str(p), params=ref_params),
                        num_boost_round=10)

        # kill pass 1 on its 4th chunk ("streaming_ingest" fault site)
        faults.clear()
        try:
            with faults.injected("streaming_ingest", fail=1, skip=3):
                with pytest.raises(InjectedFault):
                    lgb.Dataset(str(p), params=params).construct()
        finally:
            faults.clear()
        state = tmp_path / "ckpt" / "stream_state.json"
        assert state.exists()
        cursor = json.loads(state.read_text())

        # resume: picks up the saved sketch + cursor, same bytes out
        ds = lgb.Dataset(str(p), params=params).construct()
        assert ds._binned.stream_stats.resumed_from_chunk == \
            cursor["next_chunk"]
        got = lgb.train(params, lgb.Dataset(str(p), params=params),
                        num_boost_round=10)

        # the params dump legitimately differs in checkpoint_dir; the
        # trees and everything else must not
        def no_ckpt_line(s):
            return "\n".join(ln for ln in s.splitlines()
                             if not ln.startswith("[checkpoint_dir:"))
        assert no_ckpt_line(got.model_to_string()) == \
            no_ckpt_line(ref.model_to_string())
        assert not state.exists()  # cleared after a successful pass

    def test_torn_state_pair_discarded(self, tmp_path):
        # json and npz are renamed in two os.replace calls; a kill
        # between them must not resume with a cursor from chunk k over
        # a sketch from chunk k+1 — the npz's _seq copy of the cursor
        # detects the tear and load discards the pair
        from lightgbm_tpu.streaming.loader import (_load_stream_state,
                                                   _save_stream_state)
        d = str(tmp_path / "ckpt")
        _save_stream_state(d, {"phase": "sketch", "next_chunk": 3,
                               "num_features": 2, "rows": 600},
                           {"labels": np.zeros(600, np.float32)})
        state, arrays = _load_stream_state(d)
        assert state is not None and "_seq" not in arrays
        j = json.loads((tmp_path / "ckpt" / "stream_state.json").read_text())
        j["next_chunk"], j["rows"] = 2, 400
        (tmp_path / "ckpt" / "stream_state.json").write_text(json.dumps(j))
        assert _load_stream_state(d) == (None, None)

    def test_torn_state_restart_end_to_end(self, tmp_path):
        # a torn pair in the checkpoint dir restarts pass 1 from scratch
        # (resumed_from_chunk 0) and still produces the in-memory bins
        n = 1000
        X, y = make_binary(n=n, f=5, seed=17)
        p = tmp_path / "train.csv"
        write_csv(p, X, y)
        ck = tmp_path / "ckpt"
        ck.mkdir()
        from lightgbm_tpu.streaming.loader import _save_stream_state
        _save_stream_state(str(ck), {"phase": "sketch", "next_chunk": 2,
                                     "num_features": 5, "rows": 400},
                           {"labels": np.zeros(400, np.float32)})
        j = json.loads((ck / "stream_state.json").read_text())
        j["next_chunk"], j["rows"] = 1, 200
        (ck / "stream_state.json").write_text(json.dumps(j))
        params = dict(self._params(tmp_path, n), stream_chunk_rows=200)
        ds = lgb.Dataset(str(p), params=params).construct()
        assert ds._binned.stream_stats.resumed_from_chunk == 0
        assert_binned_equal(from_raw_ref(X, y), ds._binned)

    def test_pass1_saves_throttled_subquadratic(self, tmp_path,
                                                monkeypatch):
        # each save rewrites the whole sketch + label buffer, so saving
        # per chunk made checkpoint I/O O(rows^2/chunk) over the stream;
        # the geometric growth rule keeps the save count logarithmic in
        # chunks (total bytes O(N)) while the fault-window tests above
        # still see a fresh-enough cursor
        import lightgbm_tpu.streaming.loader as loader_mod
        X, y = make_binary(n=2000, f=4, seed=3)
        calls = []
        real = loader_mod._save_stream_state

        def counting(*a, **k):
            calls.append(1)
            return real(*a, **k)

        monkeypatch.setattr(loader_mod, "_save_stream_state", counting)
        build_streamed_dataset(PureStream(X, y, chunk_rows=50),
                               sample_rows=2000,
                               checkpoint_dir=str(tmp_path / "ck"))
        n_chunks = 2000 // 50
        assert 1 <= len(calls) < n_chunks // 2

    def test_state_ignored_by_checkpoint_latest(self, tmp_path):
        # stream_state.* must not be mistaken for a training checkpoint
        from lightgbm_tpu.reliability.checkpoint import latest_checkpoint
        d = tmp_path / "ckpt"
        d.mkdir()
        (d / "stream_state.json").write_text("{}")
        (d / "stream_state.npz").write_bytes(b"")
        assert latest_checkpoint(str(d)) is None


# ----------------------------------------------------------- CLI / e2e

class TestCLIStreaming:
    def test_task_train_stream_input(self, tmp_path):
        from lightgbm_tpu.cli import main
        X, y = make_binary(n=1300, f=6, seed=21)
        write_csv(tmp_path / "train.tsv", X[:1000], y[:1000], delimiter="\t")
        write_csv(tmp_path / "valid.tsv", X[1000:], y[1000:], delimiter="\t")
        (tmp_path / "train.conf").write_text(f"""
task = train
objective = binary
metric = auc
data = {tmp_path}/train.tsv
valid = {tmp_path}/valid.tsv
num_trees = 8
num_leaves = 15
stream_input = true
stream_chunk_rows = 128
stream_sample_rows = 1000
output_model = {tmp_path}/model.txt
verbosity = -1
""")
        main([f"config={tmp_path}/train.conf"])
        text = (tmp_path / "model.txt").read_text()
        assert text.startswith("tree\nversion=v3")

    def test_cli_stream_matches_in_memory(self, tmp_path):
        from lightgbm_tpu.cli import main
        X, y = make_binary(n=900, f=5, seed=22)
        write_csv(tmp_path / "train.tsv", X, y, delimiter="\t")
        base = f"""
task = train
objective = binary
data = {tmp_path}/train.tsv
num_trees = 6
num_leaves = 15
deterministic = true
verbosity = -1
"""
        (tmp_path / "mem.conf").write_text(
            base + f"output_model = {tmp_path}/mem.txt\n")
        (tmp_path / "st.conf").write_text(
            base + "stream_input = true\nstream_chunk_rows = 173\n"
            "stream_sample_rows = 900\n"
            f"output_model = {tmp_path}/st.txt\n")
        main([f"config={tmp_path}/mem.conf"])
        main([f"config={tmp_path}/st.conf"])
        # the dumped params legitimately differ (stream_* flags,
        # output_model path); the tree section must be byte-identical
        st = (tmp_path / "st.txt").read_text().split("\nparameters:")[0]
        mem = (tmp_path / "mem.txt").read_text().split("\nparameters:")[0]
        assert st == mem


# ------------------------------------------------------ observability

class TestStreamingObservability:
    def test_metrics_family_recorded(self, rng):
        from lightgbm_tpu.observability import registry as obs
        obs.enable()
        try:
            obs.reset()
            X, y = make_binary(n=800, f=4, seed=5)
            build_streamed_dataset(
                ArraySource(np.asarray(X), chunk_rows=100),
                label=np.asarray(y, np.float32), sample_rows=800)
            snap = obs.streaming_snapshot()
            assert snap["chunks"] == 8 and snap["rows"] == 800
            assert "lightgbm_tpu_streaming" in obs.prometheus_text()
        finally:
            obs.disable()


# ------------------------------------------------- 10M-row slow smoke

@pytest.mark.slow
class TestTenMillionRowSmoke:
    def test_out_of_core_bounded_memory(self):
        # 10M x 28 float64 materialized would be +2.24 GB; the streamed
        # path's working set is O(chunk + sketch) on top of the uint8
        # binned matrix (~280 MB). Subprocess so ru_maxrss is ours.
        code = textwrap.dedent("""
            import os, resource, sys
            sys.path.insert(0, os.getcwd())
            import lightgbm_tpu as lgb
            from helpers.synth import SynthSource
            rss = lambda: resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            src = SynthSource(rows=10_000_000, cols=28,
                              chunk_rows=256 * 1024, seed=17)
            before = rss()
            ds = lgb.Dataset(src, params={"max_bin": 255}).construct()
            ingest_mb = (rss() - before) / 1024.0
            st = ds._binned.stream_stats
            assert st.rows == 10_000_000, st.rows
            booster = lgb.train({"objective": "binary", "num_leaves": 7,
                                 "verbosity": -1}, ds, num_boost_round=2)
            train_mb = (rss() - before) / 1024.0 - ingest_mb
            print(f"ingest delta {ingest_mb:.0f} MB (+{train_mb:.0f} MB "
                  f"trainer buffers), {st.chunks} chunks, "
                  f"{st.rows_per_sec:.0f} rows/s, "
                  f"overlap {st.overlap_frac:.0%}")
            # the ingest bound is what this subsystem owns: uint8 binned
            # matrix (280 MB) + double-buffered chunk generation + the
            # 200k-row sketch — measured ~800 MB, vs +2.24 GB merely to
            # materialize the float64 matrix on the legacy path before
            # training could even start. The trainer's own device
            # buffers on 10M rows are unchanged by the ingestion route.
            sys.exit(0 if ingest_mb < 1200.0 else 17)
        """)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           cwd=os.path.dirname(os.path.dirname(
                               os.path.abspath(__file__))),
                           capture_output=True, text=True, timeout=1800)
        assert r.returncode == 0, f"{r.stdout}\n{r.stderr[-3000:]}"
