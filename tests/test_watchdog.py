"""Collective-watchdog unit tests — tier-1, subprocess-free.

The guard is a state machine over injectable clocks, so deadline
expiry, compile grace, and heartbeat diagnosis are all testable with
fake time; the one real-thread test stubs the abort so nothing calls
`os._exit`. The end-to-end path (a real rank dying mid-collective) is
the chaos harness's job (tests/test_chaos.py, `make chaos`)."""

import importlib
import threading

import numpy as np
import pytest

# `reliability.__init__` re-exports the `faults` *registry*, which
# shadows the submodule on attribute lookup — go through importlib to
# get the module object the monkeypatched hook lives in
faults_mod = importlib.import_module("lightgbm_tpu.reliability.faults")
from lightgbm_tpu.config import param_dict_to_config
from lightgbm_tpu.observability.registry import registry
from lightgbm_tpu.parallel.comm import (checkpoint_agree,
                                        checkpoint_coordinator,
                                        guarded_allgather)
from lightgbm_tpu.reliability.faults import (InjectedFault,
                                             RANK_DEATH_EXIT_CODE,
                                             faults)
from lightgbm_tpu.reliability.watchdog import (CollectiveGuard,
                                               FIRST_DEADLINE_FACTOR,
                                               WATCHDOG_EXIT_CODE,
                                               active_guard,
                                               collective_guard,
                                               configure_watchdog,
                                               maybe_start_watchdog,
                                               read_heartbeat_info,
                                               read_heartbeats,
                                               shutdown_watchdog,
                                               write_heartbeat)


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    yield
    faults.clear()
    shutdown_watchdog()


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# deadline state machine (fake monotonic clock)

def test_deadline_expiry_fake_clock():
    clk = FakeClock()
    g = CollectiveGuard(10.0, rank=0, world=2, clock=clk)
    assert g.poll() is None          # no active bracket, nothing to say
    g.enter("gather")
    clk.advance(11.0)
    # first bracket of a site carries the compile grace: 4x deadline
    assert g.poll() is None
    clk.advance(10.0 * FIRST_DEADLINE_FACTOR)
    diag = g.poll()
    assert diag is not None
    assert "gather" in diag and "collective_timeout_s" in diag
    g.exit_()
    assert g.poll() is None          # bracket closed: deadline cleared
    # second bracket of the SAME site: steady-state deadline, no grace
    g.enter("gather")
    clk.advance(11.0)
    assert g.poll() is not None
    g.exit_()


def test_poll_fresh_bracket_is_quiet():
    clk = FakeClock()
    g = CollectiveGuard(10.0, clock=clk, world=2)
    g.enter("x")
    clk.advance(5.0)
    assert g.poll() is None
    g.exit_()


# ---------------------------------------------------------------------------
# heartbeat files + diagnosis

def test_heartbeat_roundtrip_and_missing_dir(tmp_path):
    hb = str(tmp_path / "hb")
    write_heartbeat(hb, 0, 123.5)
    write_heartbeat(hb, 1, 99.0)
    assert read_heartbeats(hb) == {0: 123.5, 1: 99.0}
    assert read_heartbeats(str(tmp_path / "nope")) == {}


def test_heartbeat_span_payload_roundtrip(tmp_path):
    hb = str(tmp_path / "hb")
    write_heartbeat(hb, 0, 123.5, span_name="collective:sharded_grow",
                    span_age=12.25)
    write_heartbeat(hb, 1, 99.0)                   # no open span
    info = read_heartbeat_info(hb)
    assert info[0] == (123.5, "collective:sharded_grow", 12.25)
    assert info[1] == (99.0, "", 0.0)
    # the stamp-only view is unchanged by the span tag
    assert read_heartbeats(hb) == {0: 123.5, 1: 99.0}


def test_heartbeat_old_single_line_format_parses(tmp_path):
    # files written by a pre-span-payload build: one line, repr(float)
    hb = tmp_path / "hb"
    hb.mkdir()
    (hb / "hb_rank_002").write_text(repr(456.75))
    assert read_heartbeat_info(str(hb)) == {2: (456.75, "", 0.0)}


def test_diagnosis_names_stale_ranks_span(tmp_path):
    hb = str(tmp_path / "hb")
    wall = FakeClock(500.0)
    g = CollectiveGuard(1.0, rank=0, world=2, heartbeat_dir=hb,
                        heartbeat_interval_s=1.0, wall=wall)
    write_heartbeat(hb, 0, 500.0)
    write_heartbeat(hb, 1, 488.0,
                    span_name="collective:sharded_grow", span_age=3.0)
    diag = g.diagnose("sharded_grow")
    assert ("rank 1 last seen 12.0s ago in span "
            "collective:sharded_grow") in diag


def test_stale_heartbeat_diagnosis_names_right_rank(tmp_path):
    hb = str(tmp_path / "hb")
    wall = FakeClock(500.0)
    g = CollectiveGuard(10.0, rank=0, world=3, heartbeat_dir=hb,
                        heartbeat_interval_s=1.0, wall=wall)
    write_heartbeat(hb, 0, 500.0)    # self: fresh
    write_heartbeat(hb, 1, 450.0)    # peer: 50s stale — the culprit
    # rank 2 never wrote a heartbeat at all
    diag = g.diagnose("gather")
    assert "rank 1 last seen 50.0s ago" in diag
    assert "rank 2 never heartbeat" in diag
    assert "rank 0 last seen" not in diag


def test_fresh_heartbeats_reported_as_fresh(tmp_path):
    hb = str(tmp_path / "hb")
    wall = FakeClock(500.0)
    g = CollectiveGuard(10.0, rank=0, world=2, heartbeat_dir=hb,
                        heartbeat_interval_s=1.0, wall=wall)
    write_heartbeat(hb, 0, 500.0)
    write_heartbeat(hb, 1, 499.5)
    assert "heartbeats fresh" in g.diagnose("gather")


def test_diagnosis_without_heartbeat_dir():
    g = CollectiveGuard(10.0, rank=1, world=2)
    diag = g.diagnose("gather")
    assert "rank 1" in diag and "heartbeat_dir" in diag


# ---------------------------------------------------------------------------
# disabled-by-default contracts (the tier-1 fast path)

def test_guard_disabled_by_default_on_one_machine():
    cfg = param_dict_to_config({"verbosity": -1})
    assert cfg.collective_timeout_s == 0.0
    assert maybe_start_watchdog(cfg) is None
    assert active_guard() is None
    # explicit timeout, but a single process: still no guard
    cfg2 = param_dict_to_config(
        {"collective_timeout_s": 5.0, "verbosity": -1})
    assert maybe_start_watchdog(cfg2) is None
    assert active_guard() is None


def test_configure_watchdog_needs_world_and_timeout():
    assert configure_watchdog(0.0, world=8) is None
    assert configure_watchdog(10.0, world=1) is None
    assert active_guard() is None
    with pytest.raises(ValueError):
        CollectiveGuard(0.0)


def test_collective_guard_noop_without_guard():
    assert active_guard() is None
    with collective_guard("anything"):
        pass                          # must not raise, log, or record


def test_single_process_coordinator_is_none():
    assert checkpoint_coordinator() is None


# ---------------------------------------------------------------------------
# guarded_allgather: the bracketed choke point

def test_guarded_allgather_single_process_identity():
    out = guarded_allgather(np.arange(6).reshape(2, 3), label="t")
    np.testing.assert_array_equal(np.asarray(out).reshape(2, 3),
                                  np.arange(6).reshape(2, 3))


def test_guarded_allgather_carries_collective_psum_site():
    with faults.injected("collective_psum", fail=1):
        with pytest.raises(InjectedFault):
            guarded_allgather(np.zeros(3))
    # schedule consumed: next call clean
    np.asarray(guarded_allgather(np.zeros(3)))


def test_checkpoint_agree_single_process():
    out = checkpoint_agree(17)
    assert list(np.asarray(out).reshape(-1)) == [17]


def test_injected_fault_passes_guard_bracket_silently():
    clk = FakeClock()
    g = CollectiveGuard(10.0, world=2, clock=clk)
    with pytest.raises(InjectedFault):
        with g.guard("site"):
            raise InjectedFault("collective_psum")
    assert g.poll() is None           # bracket was closed on the way out


def test_other_exceptions_reraise_with_diagnosis(tmp_path, capsys):
    hb = str(tmp_path / "hb")
    wall = FakeClock(500.0)
    g = CollectiveGuard(10.0, rank=0, world=2, heartbeat_dir=hb,
                        heartbeat_interval_s=1.0, wall=wall)
    write_heartbeat(hb, 1, 480.0)
    with pytest.raises(RuntimeError, match="boom"):
        with g.guard("site"):
            raise RuntimeError("boom")
    err = capsys.readouterr().err
    assert "rank 1 last seen" in err


# ---------------------------------------------------------------------------
# monitor thread (real time, stubbed abort — nothing calls os._exit)

def test_monitor_thread_fires_stubbed_abort(tmp_path):
    fired = threading.Event()
    seen = {}

    def _abort(diag):
        seen["diag"] = diag
        fired.set()

    before = registry.collective_snapshot()
    g = CollectiveGuard(0.08, rank=0, world=2,
                        heartbeat_dir=str(tmp_path / "hb"),
                        heartbeat_interval_s=0.02,
                        first_deadline_factor=1.0, abort_fn=_abort)
    g.start()
    try:
        g.enter("gather")
        assert fired.wait(timeout=10.0), "watchdog monitor never fired"
    finally:
        g.exit_()
        g.stop()
    assert "gather" in seen["diag"]
    after = registry.collective_snapshot()
    assert after["timeouts"] > before["timeouts"]
    assert after["aborts"] > before["aborts"]


def test_exit_codes_are_distinct_and_nonzero():
    assert WATCHDOG_EXIT_CODE != RANK_DEATH_EXIT_CODE
    assert WATCHDOG_EXIT_CODE not in (0, 1)
    assert RANK_DEATH_EXIT_CODE not in (0, 1)


# ---------------------------------------------------------------------------
# rank_death fault mode (the chaos harness's kill switch)

def test_rank_death_mode_fires_exit_hook(monkeypatch):
    killed = []
    monkeypatch.setattr(faults_mod, "_rank_death_exit", killed.append)
    faults.schedule("collective_psum", fail=1, skip=1,
                    mode="rank_death")
    faults.inject("collective_psum")          # skip consumed, alive
    assert killed == []
    faults.inject("collective_psum")          # fires: "dies" here
    assert killed == ["collective_psum"]
    faults.inject("collective_psum")          # schedule consumed
    assert killed == ["collective_psum"]
    assert faults.trips("collective_psum") == 1


def test_rank_death_env_suffix(monkeypatch):
    killed = []
    monkeypatch.setattr(faults_mod, "_rank_death_exit", killed.append)
    monkeypatch.setenv("LGBM_TPU_TEST_RD", "1:1:rank_death")
    faults.schedule_from_env("collective_psum", "LGBM_TPU_TEST_RD")
    assert faults.remaining("collective_psum") == (1, 1)
    faults.inject("collective_psum")
    faults.inject("collective_psum")
    assert killed == ["collective_psum"]


def test_unknown_fault_mode_rejected():
    with pytest.raises(ValueError, match="rank_death"):
        faults.schedule("collective_psum", fail=1, mode="explode")


# ---------------------------------------------------------------------------
# observability surface

def test_collective_family_in_snapshot_and_prometheus():
    snap = registry.snapshot()
    assert set(snap["collective"]) == {
        "guarded", "wall_seconds", "timeouts", "aborts",
        "heartbeat_age_max_s", "world"}
    text = registry.prometheus_text()
    assert "lightgbm_tpu_collective_guarded" in text
    assert "lightgbm_tpu_collective_timeouts" in text
